"""Process-wide metrics registry — counters, gauges, histograms.

The serving stack grew one private stats island per subsystem
(``RpcStats``, ``PipelineStats``, batcher ``stats``, breaker counts,
chip leases) with no shared identity: answering "what is this worker
doing" meant reading four ``describe()`` dicts that never line up.
This module is the one place request-path telemetry accumulates:

- **First-class metrics** — ``counter`` / ``gauge`` / ``histogram``
  return process-wide metric families; ``.labels(...)`` hands back a
  child whose hot path is one dict lookup + one small lock (children
  are cached, label tuples interned by the dict itself). Histograms
  use explicit buckets (Prometheus convention: cumulative ``le``).
- **Collectors** — existing stats objects stay the single source of
  truth for their ``describe()`` schemas; they register a zero-cost
  callback that converts their counters into samples at *scrape* time.
  No double bookkeeping: the request path mutates one object, and
  ``describe()`` and ``/metrics`` both read it.

Rendered two ways: :func:`collect` (a JSON-able snapshot for the
``get_metrics`` worker verb) and :func:`render_prometheus` (text
exposition format v0.0.4 for ``GET /metrics``).

Label discipline: keep cardinality bounded by things an operator can
enumerate — app, deployment, replica, method family — never user ids
or request ids (those belong on traces, utils/tracing.py).
"""

from __future__ import annotations

import bisect
import logging
import math
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Optional, Sequence

_collector_logger = logging.getLogger("bioengine.metrics")

# Prometheus-convention latency buckets (seconds). Explicit, not
# exponential-by-config: the serve path spans ~1 ms (cache-hit CPU
# calls) to minutes (cold compiles), and fixed edges keep dashboards
# comparable across workers.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# Batch-occupancy buckets (requests per dispatched group) for the
# scheduler_* family: powers of two up to the largest group any ladder
# bucket realistically pads to — occupancy is the lever cross-replica
# coalescing exists to move, so it gets first-class edges.
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class _Child:
    """One labeled series. Base for Counter/Gauge children."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount


class GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class HistogramChild:
    __slots__ = ("_lock", "_edges", "_counts", "_sum", "_count")

    def __init__(self, edges: Sequence[float]):
        self._lock = threading.Lock()
        self._edges = list(edges)
        self._counts = [0] * (len(self._edges) + 1)  # + overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self._edges, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> dict:
        """Cumulative bucket counts keyed by upper edge (rendered as
        strings — the snapshot crosses the RPC plane, and msgpack's
        strict_map_key rejects float keys), plus sum/count and the
        quantile estimates operators actually read."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum = 0
        buckets = {}
        for edge, n in zip(self._edges, counts):
            cum += n
            buckets[_fmt(edge)] = cum
        return {
            "buckets": buckets,
            "count": total,
            "sum": round(s, 6),
            "p50": self._quantile(counts, total, 0.50),
            "p95": self._quantile(counts, total, 0.95),
            "p99": self._quantile(counts, total, 0.99),
        }

    def _quantile(self, counts: list, total: int, q: float) -> Optional[float]:
        """Upper-edge estimate of quantile ``q`` (None when empty,
        inf when it lands in the overflow bucket)."""
        if total == 0:
            return None
        target = math.ceil(q * total)
        cum = 0
        for edge, n in zip(self._edges, counts):
            cum += n
            if cum >= target:
                return edge
        return math.inf


OVERFLOW_LABEL = "__overflow__"

_MAX_CHILDREN: Optional[int] = None


def _max_children() -> int:
    """Per-family child cap (``BIOENGINE_METRICS_MAX_LABELS``, default
    1000). Read once — labels() can sit on warm request paths."""
    global _MAX_CHILDREN
    if _MAX_CHILDREN is None:
        import os

        _MAX_CHILDREN = int(
            os.environ.get("BIOENGINE_METRICS_MAX_LABELS", "1000")
        )
    return _MAX_CHILDREN


class _Family:
    """A named metric family with a fixed label schema.

    Cardinality guard: a hostile or buggy caller feeding unbounded
    label values (e.g. arbitrary ``method`` strings) would otherwise
    grow the child map — and the process — without bound. At
    ``BIOENGINE_METRICS_MAX_LABELS`` distinct children the family
    folds every NEW label set into one ``__overflow__`` child, warns
    once, and counts the drops in ``metrics_dropped_labels_total`` so
    the truncation is visible on the same scrape it protects."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self._overflow_warned = False

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values: Any) -> Any:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got {values}"
            )
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if (
                        self.labelnames
                        and len(self._children) >= _max_children()
                    ):
                        return self._overflow_child_locked()
                    child = self._children[key] = self._make_child()
        return child

    def _overflow_child_locked(self):
        """Called under self._lock: the shared sink child for label
        sets past the cap."""
        okey = (OVERFLOW_LABEL,) * len(self.labelnames)
        child = self._children.get(okey)
        if child is None:
            child = self._children[okey] = self._make_child()
        if not self._overflow_warned:
            self._overflow_warned = True
            _collector_logger.warning(
                f"metric family '{self.name}' hit the label-cardinality "
                f"cap ({_max_children()}); folding new label sets into "
                f"'{OVERFLOW_LABEL}' (raise BIOENGINE_METRICS_MAX_LABELS "
                f"if this cardinality is intentional)"
            )
        # DROPPED_LABELS is a plain family whose own cardinality is
        # bounded by the number of registered families; never recurse
        # into ourselves if the guard family itself ever hits the cap
        if self.name != "metrics_dropped_labels_total":
            DROPPED_LABELS.labels(self.name).inc()
        return child

    def items(self) -> list[tuple[tuple, Any]]:
        with self._lock:
            return list(self._children.items())


class Counter(_Family):
    kind = "counter"

    def _make_child(self):
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)  # unlabeled convenience


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self):
        return GaugeChild()

    def set(self, value: float) -> None:
        self.labels().set(value)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, labelnames, buckets=LATENCY_BUCKETS_S):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self.labels().observe(value)


class Sample:
    """One collector-produced series: collectors turn a live stats
    object (RpcStats, PipelineStats, batcher stats) into samples at
    scrape time instead of double-writing on the hot path."""

    __slots__ = ("name", "labels", "value", "kind", "help")

    def __init__(
        self,
        name: str,
        value: float,
        labels: Optional[dict] = None,
        kind: str = "gauge",
        help: str = "",
    ):
        self.name = name
        self.value = value
        self.labels = labels or {}
        self.kind = kind
        self.help = help


CollectorFn = Callable[[], Iterable[Sample]]


class MetricsRegistry:
    def __init__(self, namespace: str = "bioengine"):
        self.namespace = namespace
        self._metrics: dict[str, _Family] = {}
        self._collectors: dict[str, CollectorFn] = {}
        self._lock = threading.Lock()

    # ---- first-class metrics ------------------------------------------------

    def _register(self, metric: _Family) -> _Family:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric) or (
                    existing.labelnames != metric.labelnames
                ):
                    raise ValueError(
                        f"metric '{metric.name}' re-registered with a "
                        f"different type or label schema"
                    )
                return existing
            # process-lifetime family registry: families are module-
            # level singletons, never torn down while the process lives
            # bioengine: ignore[BE-LIFE-401]
            self._metrics[metric.name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._register(Counter(name, help, labelnames))  # type: ignore[return-value]

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._register(Gauge(name, help, labelnames))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = LATENCY_BUCKETS_S,
    ) -> Histogram:
        return self._register(Histogram(name, help, labelnames, buckets))  # type: ignore[return-value]

    # ---- collectors ---------------------------------------------------------

    def register_collector(self, name: str, fn: CollectorFn) -> None:
        """Scrape-time sample source (idempotent by name — re-import
        of a module that registers at import time must not stack)."""
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def _collector_samples(self) -> list[Sample]:
        with self._lock:
            collectors = list(self._collectors.items())
        out: list[Sample] = []
        for cname, fn in collectors:
            try:
                out.extend(fn())
            except Exception as e:  # noqa: BLE001 — one bad collector
                # never breaks the whole scrape; it does leave a trace
                _collector_logger.debug(f"collector '{cname}' failed: {e}")
        return out

    # ---- export -------------------------------------------------------------

    def collect(self) -> dict:
        """JSON-able snapshot (the ``get_metrics`` verb)."""
        out: dict[str, Any] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            series = []
            for key, child in m.items():
                labels = dict(zip(m.labelnames, key))
                if isinstance(child, HistogramChild):
                    series.append({"labels": labels, **child.snapshot()})
                else:
                    series.append({"labels": labels, "value": child.value})
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        for s in self._collector_samples():
            entry = out.setdefault(
                s.name, {"type": s.kind, "help": s.help, "series": []}
            )
            entry["series"].append({"labels": s.labels, "value": s.value})
        return out

    def render_prometheus(self) -> str:
        """Text exposition format 0.0.4."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            full = f"{self.namespace}_{m.name}"
            if m.help:
                lines.append(f"# HELP {full} {_escape_help(m.help)}")
            lines.append(f"# TYPE {full} {m.kind}")
            for key, child in m.items():
                labels = dict(zip(m.labelnames, key))
                if isinstance(child, HistogramChild):
                    snap = child.snapshot()
                    for edge, cum in snap["buckets"].items():
                        lines.append(
                            _line(
                                f"{full}_bucket",
                                {**labels, "le": edge},
                                cum,
                            )
                        )
                    lines.append(
                        _line(
                            f"{full}_bucket",
                            {**labels, "le": "+Inf"},
                            snap["count"],
                        )
                    )
                    lines.append(_line(f"{full}_sum", labels, snap["sum"]))
                    lines.append(_line(f"{full}_count", labels, snap["count"]))
                else:
                    lines.append(_line(full, labels, child.value))
        # collector samples, grouped so TYPE headers appear once
        grouped: dict[str, list[Sample]] = {}
        for s in self._collector_samples():
            grouped.setdefault(s.name, []).append(s)
        for name, samples in grouped.items():
            full = f"{self.namespace}_{name}"
            if samples[0].help:
                lines.append(f"# HELP {full} {_escape_help(samples[0].help)}")
            lines.append(f"# TYPE {full} {samples[0].kind}")
            for s in samples:
                lines.append(_line(full, s.labels, s.value))
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Prometheus float formatting: integral values without the dot."""
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _line(name: str, labels: dict, value: float) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"


# ---------------------------------------------------------------------------
# The process-wide default registry + module-level conveniences
# ---------------------------------------------------------------------------

REGISTRY = MetricsRegistry()

# the cardinality guard's visible half: how many label sets each family
# folded into its __overflow__ child (labelled by family, so its own
# cardinality is bounded by the number of registered families)
DROPPED_LABELS = REGISTRY.counter(
    "metrics_dropped_labels_total",
    "label sets folded into __overflow__ by the cardinality guard",
    ("family",),
)

_ENABLED: Optional[bool] = None


def metrics_enabled() -> bool:
    """Hot-path kill-switch (``BIOENGINE_METRICS=0``): gates the
    *optional* request-path observations (latency histograms, park
    times). Counters that back existing ``describe()`` schemas always
    run — they replaced the plain ints those schemas already paid for.
    Read once; tests flip it via :func:`reset_env_cache`."""
    global _ENABLED
    if _ENABLED is None:
        import os

        _ENABLED = os.environ.get("BIOENGINE_METRICS", "1") != "0"
    return _ENABLED


def reset_env_cache() -> None:
    global _ENABLED, _MAX_CHILDREN
    _ENABLED = None
    _MAX_CHILDREN = None


def counter(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(
    name: str,
    help: str = "",
    labelnames: Sequence[str] = (),
    buckets: Sequence[float] = LATENCY_BUCKETS_S,
) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets)


def register_collector(name: str, fn: CollectorFn) -> None:
    REGISTRY.register_collector(name, fn)


def collect() -> dict:
    return REGISTRY.collect()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


# ---------------------------------------------------------------------------
# Instance-set collectors — the pattern the stats islands plug in with
# ---------------------------------------------------------------------------


class InstanceSet:
    """Weak set of live stats objects plus a collector that folds them
    into samples at scrape time. ``RpcStats``/``PipelineStats``/batcher
    instances register at construction; a dead replica's stats object
    drops out with the garbage collector, no unregister bookkeeping."""

    def __init__(self, name: str, fold: Callable[[list], Iterable[Sample]]):
        self._instances: "weakref.WeakSet" = weakref.WeakSet()
        self._fold = fold
        register_collector(name, self._collect)

    def add(self, obj: Any) -> None:
        self._instances.add(obj)

    def _collect(self) -> Iterable[Sample]:
        return self._fold(list(self._instances))


# ---------------------------------------------------------------------------
# Process self-metrics: event-loop lag, RSS, open fds, GC pauses
# ---------------------------------------------------------------------------
#
# The serving plane measures requests; these measure the PROCESS the
# requests run in — the numbers that explain a latency regression no
# request-level metric can (a blocked event loop, a leak marching RSS
# toward the OOM killer, fd exhaustion, GC pressure). All are
# scrape-time reads except the loop-lag gauge, which a supervised
# ticker samples (a scrape can't observe the loop from inside a
# blocked loop), and GC pauses, which gc callbacks accumulate.

_proc_lock = threading.Lock()
_loop_lag = {"last_s": 0.0, "max_s": 0.0, "samples": 0}
# gc stats are LOCK-FREE by design: gc.callbacks run synchronously on
# whatever thread's allocation crossed the collection threshold — if
# that thread already holds a lock the callback needs (e.g. a scrape
# holding _proc_lock allocating its snapshot), a locking callback
# self-deadlocks and wedges the process. Plain GIL-protected updates
# suffice; readers may see a value one collection stale. Generations
# are pre-seeded so the dict never changes size under an iterating
# reader.
_gc_stats: dict[str, Any] = {
    "pause_seconds": 0.0,
    "collections": {0: 0, 1: 0, 2: 0},   # generation -> count
    "collected": 0,
    "start_mono": None,
    "installed": False,
}
_loop_monitor_running = False


def _gc_callback(phase: str, info: dict) -> None:
    # module-global time, no lazy import: this callback outlives the
    # import machinery (gc runs during interpreter shutdown). NO locks
    # here — see the note on _gc_stats.
    if phase == "start":
        _gc_stats["start_mono"] = time.monotonic()
        return
    start = _gc_stats["start_mono"]
    if start is not None:
        _gc_stats["pause_seconds"] += time.monotonic() - start
        _gc_stats["start_mono"] = None
    gen = info.get("generation", 0)
    counts = _gc_stats["collections"]
    counts[gen] = counts.get(gen, 0) + 1
    _gc_stats["collected"] += info.get("collected", 0)


def _read_rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os as _os

        return float(pages * _os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        try:
            import resource

            # ru_maxrss is PEAK rss in KiB on linux — a coarser truth
            # than live rss, still the right alarm signal
            return float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
            )
        except Exception:  # noqa: BLE001 — no rss source on this platform
            return None


def _count_open_fds() -> Optional[float]:
    try:
        import os as _os

        return float(len(_os.listdir("/proc/self/fd")))
    except OSError:
        return None


def _collect_process() -> Iterable[Sample]:
    out: list[Sample] = []
    rss = _read_rss_bytes()
    if rss is not None:
        out.append(
            Sample(
                "process_rss_bytes", rss,
                help="resident set size of this process",
            )
        )
    fds = _count_open_fds()
    if fds is not None:
        out.append(
            Sample(
                "process_open_fds", fds,
                help="open file descriptors (sockets, shm maps, logs)",
            )
        )
    with _proc_lock:
        lag_last, lag_max, lag_n = (
            _loop_lag["last_s"], _loop_lag["max_s"], _loop_lag["samples"],
        )
    # gc stats read OUTSIDE the lock (the gc callback is lock-free and
    # the collections dict never changes size — generations pre-seeded)
    gc_pause = _gc_stats["pause_seconds"]
    gc_colls = dict(_gc_stats["collections"])
    gc_collected = _gc_stats["collected"]
    if lag_n:
        out.append(
            Sample(
                "event_loop_lag_seconds", round(lag_last, 6),
                help="latest sampled event-loop scheduling lag",
            )
        )
        out.append(
            Sample(
                "event_loop_lag_max_seconds", round(lag_max, 6),
                help="worst event-loop lag since process start",
            )
        )
    out.append(
        Sample(
            "gc_pause_seconds_total", round(gc_pause, 6), kind="counter",
            help="cumulative stop-the-world gc pause time",
        )
    )
    for gen, n in sorted(gc_colls.items()):
        out.append(
            Sample(
                "gc_collections_total", n, {"generation": str(gen)},
                kind="counter", help="gc runs by generation",
            )
        )
    out.append(
        Sample(
            "gc_collected_objects_total", gc_collected, kind="counter",
            help="objects reclaimed by the cyclic gc",
        )
    )
    return out


def install_process_metrics() -> None:
    """Register the process collector + gc callbacks (idempotent —
    worker and worker_host both call this at startup; an in-process
    test harness hosting several of them installs once)."""
    register_collector("process", _collect_process)
    if not _gc_stats["installed"]:
        import gc

        gc.callbacks.append(_gc_callback)
        _gc_stats["installed"] = True


async def monitor_event_loop(interval_s: float = 0.5) -> None:
    """Supervised ticker: sleep ``interval_s``, measure the overshoot —
    that overshoot IS the event-loop scheduling lag every coroutine in
    this process experiences. Runs forever; spawn it supervised and
    cancel at shutdown. A second ticker in the same process returns
    immediately (one sampler is the truth)."""
    import asyncio

    global _loop_monitor_running
    if _loop_monitor_running:
        return
    _loop_monitor_running = True
    try:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(interval_s)
            lag = max(0.0, (time.monotonic() - t0) - interval_s)
            with _proc_lock:
                _loop_lag["last_s"] = lag
                _loop_lag["max_s"] = max(_loop_lag["max_s"], lag)
                _loop_lag["samples"] += 1
    finally:
        _loop_monitor_running = False
