"""Network helpers: internal-IP detection and free-port acquisition.

Capability parity with ref bioengine/utils/network.py (SIOCGIFADDR
interface scan preferring RFC-1918 addresses; free-port scan that can
hold the socket until handoff to avoid TOCTOU races).
"""

from __future__ import annotations

import socket
import struct
from typing import Optional


def get_internal_ip() -> str:
    """Best-effort internal IP: UDP-connect trick, fallback to loopback."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
        return ip
    except OSError:
        pass
    try:
        import fcntl  # POSIX only
        import ipaddress

        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            for ifname in _interface_names():
                try:
                    packed = struct.pack("256s", ifname[:15].encode())
                    addr = fcntl.ioctl(s.fileno(), 0x8915, packed)[20:24]
                    ip = socket.inet_ntoa(addr)
                    parsed = ipaddress.ip_address(ip)
                    if parsed.is_private and not parsed.is_loopback:
                        return ip
                except OSError:
                    continue
    except ImportError:
        pass
    return "127.0.0.1"


def _interface_names() -> list[str]:
    try:
        with open("/proc/net/dev") as f:
            return [
                line.split(":")[0].strip()
                for line in f.readlines()[2:]
                if ":" in line
            ]
    except OSError:
        return ["eth0", "en0", "lo"]


def acquire_free_port(
    start: int = 0,
    end: Optional[int] = None,
    hold: bool = False,
) -> tuple[int, Optional[socket.socket]]:
    """Find a free TCP port.

    With ``start=0`` the OS picks one. With a range, scan sequentially —
    mirrors ref bioengine/cluster/ray_cluster.py:480-532 which holds the
    bound socket until the consumer process starts (``hold=True``).
    Returns (port, held_socket_or_None); caller closes the held socket.
    """
    candidates = [0] if start == 0 else range(start, (end or start + 100) + 1)
    for port in candidates:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("0.0.0.0", port))
        except OSError:
            s.close()
            continue
        actual = s.getsockname()[1]
        if hold:
            return actual, s
        s.close()
        return actual, None
    raise RuntimeError(f"No free port found in range {start}-{end}")
