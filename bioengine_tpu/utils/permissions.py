"""User/workspace permission checks for service methods.

Behavior parity with ref bioengine/utils/permissions.py:30-104 — a caller
context carries ``user: {id, email}`` and ``ws``; authorization lists may
contain ``"*"`` (any authenticated user), user ids, emails, or workspaces.
An empty/None authorization list denies every caller.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional


class PermissionError_(PermissionError):
    """Raised when a caller is not authorized for a method."""


def create_context(
    user_id: str = "anonymous",
    email: Optional[str] = None,
    workspace: str = "public",
) -> dict[str, Any]:
    """Build the context dict passed to every service method."""
    return {
        "user": {"id": user_id, "email": email or f"{user_id}@local"},
        "ws": workspace,
    }


def check_permissions(
    context: Optional[dict[str, Any]],
    authorized_users: Optional[Iterable[str]],
    resource_name: str = "resource",
) -> None:
    """Raise PermissionError unless the context's user is authorized.

    Match order mirrors the reference: wildcard, user id, user email,
    workspace. Empty authorized list denies all.
    """
    if context is None or "user" not in context:
        raise PermissionError_(
            f"Missing user context for access to {resource_name}"
        )
    user = context["user"] or {}
    user_id = user.get("id")
    email = user.get("email")
    workspace = context.get("ws")

    allowed = list(authorized_users or [])
    if not allowed:
        raise PermissionError_(
            f"No users are authorized to access {resource_name}"
        )
    for entry in allowed:
        if entry == "*":
            return
        if user_id and entry == user_id:
            return
        if email and entry == email:
            return
        if workspace and entry == workspace:
            return
    raise PermissionError_(
        f"User '{user_id}' is not authorized to access {resource_name}"
    )


def check_method_permission(
    acl: "list | dict", method: str, context: Optional[dict]
) -> None:
    """Per-method ACL: method-specific entry > wildcard entry > deny
    (ref bioengine/apps/proxy_deployment.py:345-403)."""
    if isinstance(acl, dict):
        users = acl.get(method, acl.get("*"))
    else:
        users = acl
    check_permissions(context, users, resource_name=f"method '{method}'")


def is_authorized(
    context: Optional[dict[str, Any]], authorized_users: Optional[Iterable[str]]
) -> bool:
    try:
        check_permissions(context, authorized_users)
        return True
    except PermissionError:
        return False
