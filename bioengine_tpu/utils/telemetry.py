"""Telemetry history — the fixed-memory multi-resolution series store.

The metrics plane (PR 6) answers "what is this worker doing *right
now*"; nothing in the system remembers what it was doing five minutes
ago, so questions like "is this deployment meeting its latency target
this hour" (the SLO engine, serving/slo.py) or "what did load look
like before the page" (the GDP-style learned-placement feature stream,
PAPERS.md) had no substrate. This module is that substrate:

- **Snapshots, not scrapes.** A :class:`RegistrySampler` diffs two
  successive ``metrics.collect()`` snapshots into per-deployment
  DELTAS — counters become per-interval counts, histogram buckets
  become per-interval bucket counts, gauges are point-sampled. Worker
  hosts run one and push the result to the controller over the
  existing RPC plane (capability ``telem1``, worker_host.py); the
  controller runs its own over the local registry. Either way the
  store never touches the hot path — it consumes what the registry
  already accumulates.
- **Fixed memory.** :class:`TelemetryStore` keeps, per deployment and
  per resolution, a ring of time-aligned buckets
  (default ``10s x 360 / 1m x 180 / 5m x 288`` — one hour of fine
  grain, three of medium, a day of coarse). Rings are bounded deques;
  the deployment-key set is bounded too (LRU eviction at
  ``BIOENGINE_TELEM_MAX_SERIES``), so a deploy/undeploy churn loop or
  a hostile push stream cannot grow the store.
- **Reconstructable series.** :meth:`TelemetryStore.series` turns the
  stored deltas back into the series operators ask for — request/error
  rates, latency quantiles re-estimated from merged histogram buckets
  (same upper-edge estimator as the live registry, so the two agree
  within quantile-bucket error), queue depth, chip-seconds, shed
  counts — and :meth:`window_aggregate` folds a wall-clock window into
  the totals the SLO burn-rate math consumes.

Env knobs: ``BIOENGINE_TELEM_RES`` overrides the resolution ladder
(``"10x360,60x180,300x288"`` — step seconds x slots),
``BIOENGINE_TELEM_MAX_SERIES`` bounds distinct deployment keys
(default 256), ``BIOENGINE_TELEM_PUSH_S`` is the sampler cadence
(read by worker_host/controller, default 10).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

# step seconds x slots, finest first (series/window selection walks in
# order and picks the finest ring that covers the request)
DEFAULT_RESOLUTIONS: tuple[tuple[float, int], ...] = (
    (10.0, 360),   # 1 h of 10 s grain
    (60.0, 180),   # 3 h of 1 m grain
    (300.0, 288),  # 24 h of 5 m grain
)

DEFAULT_MAX_SERIES = 256

# the numeric per-interval delta fields a snapshot may carry for one
# deployment (summed on ingest; anything else is ignored — the wire
# format is forward-compatible by construction)
_SUM_FIELDS = (
    "requests",
    "errors",
    "shed",
    "chip_seconds",
    "latency_sum",
    "replica_requests",
    # token streaming (DeploymentHandle.call_stream): generated-token
    # count and the inter-token gap histogram's count — the SLO
    # engine's inter_token_ms objective burns against these
    "tokens",
    "inter_token_count",
)
# gauges: point-sampled, last-write-wins within a bucket
_GAUGE_FIELDS = ("queue_depth",)
# bucket-delta dicts {upper_edge_str: count}
_BUCKET_FIELDS = (
    "latency_buckets",
    "replica_latency_buckets",
    "inter_token_buckets",
)

SERIES_NAMES = (
    "request_rate",
    "error_rate",
    "error_ratio",
    "shed_rate",
    "chip_seconds",
    "queue_depth",
    "latency_p50",
    "latency_p95",
    "latency_p99",
    "replica_latency_p99",
    "tokens_per_second",
    "inter_token_p99",
)


def resolutions_from_env() -> tuple[tuple[float, int], ...]:
    raw = os.environ.get("BIOENGINE_TELEM_RES")
    if not raw:
        return DEFAULT_RESOLUTIONS
    out = []
    for part in raw.split(","):
        step, _, slots = part.strip().partition("x")
        out.append((float(step), max(2, int(slots))))
    return tuple(sorted(out)) or DEFAULT_RESOLUTIONS


def _merge_buckets(dst: dict, src: dict) -> None:
    for edge, n in (src or {}).items():
        dst[edge] = dst.get(edge, 0) + n


def quantile_from_buckets(
    buckets: dict, total: Optional[float], q: float
) -> Optional[float]:
    """Upper-edge quantile estimate over per-interval (cumulative-form)
    bucket counts — the same estimator HistogramChild uses, so stored
    history and the live registry agree within bucket error. ``total``
    falls back to the largest cumulative count when absent."""
    if not buckets:
        return None
    edges = sorted(
        ((float(e) if e != "+Inf" else math.inf), c)
        for e, c in buckets.items()
    )
    n = total if total is not None else (edges[-1][1] if edges else 0)
    if not n:
        return None
    target = math.ceil(q * n)
    for edge, cum in edges:
        if cum >= target:
            return edge
    return math.inf


class _Bucket:
    """One time-aligned slot of one ring."""

    __slots__ = ("t", "span_s", "sums", "gauges", "buckets", "samples")

    def __init__(self, t: float, span_s: float):
        self.t = t                    # bucket start (wall clock, aligned)
        self.span_s = span_s
        self.sums: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.buckets: dict[str, dict] = {}
        self.samples = 0

    def add(self, snap: dict) -> None:
        self.samples += 1
        for f in _SUM_FIELDS:
            v = snap.get(f)
            if v:
                self.sums[f] = self.sums.get(f, 0.0) + float(v)
        for f in _GAUGE_FIELDS:
            v = snap.get(f)
            if v is not None:
                self.gauges[f] = float(v)
        for f in _BUCKET_FIELDS:
            v = snap.get(f)
            if v:
                _merge_buckets(self.buckets.setdefault(f, {}), v)

    def merged_into(self, acc: dict) -> None:
        for f, v in self.sums.items():
            acc[f] = acc.get(f, 0.0) + v
        for f, v in self.buckets.items():
            _merge_buckets(acc.setdefault(f, {}), v)


class _DeploymentSeries:
    """All resolutions for one (app, deployment)."""

    def __init__(self, resolutions: tuple[tuple[float, int], ...]):
        self.rings: list[tuple[float, deque]] = [
            (step, deque(maxlen=slots)) for step, slots in resolutions
        ]
        self.updated_at = 0.0

    def add(self, captured_at: float, snap: dict) -> None:
        self.updated_at = captured_at
        for step, ring in self.rings:
            start = math.floor(captured_at / step) * step
            if ring and ring[-1].t == start:
                ring[-1].add(snap)
            elif ring and ring[-1].t > start:
                # late sample from a skewed pusher: fold into the
                # newest bucket rather than corrupting ring order
                ring[-1].add(snap)
            else:
                b = _Bucket(start, step)
                b.add(snap)
                ring.append(b)

    def ring_for(
        self, since: Optional[float], resolution: Optional[float], now: float
    ) -> tuple[float, deque]:
        if resolution is not None:
            # exact or next-coarser match
            for step, ring in self.rings:
                if step >= resolution - 1e-9:
                    return step, ring
            return self.rings[-1]
        if since is None:
            return self.rings[0]
        span = now - since
        for step, ring in self.rings:
            if step * ring.maxlen >= span:
                return step, ring
        return self.rings[-1]


class TelemetryStore:
    """Controller-side store of per-deployment telemetry history.

    Thread-safe (pushes arrive on the RPC plane while scrapes read).
    Every public reader returns JSON-able data — series cross the RPC
    plane via ``get_telemetry`` and land in incident bundles."""

    def __init__(
        self,
        resolutions: Optional[Iterable[tuple[float, int]]] = None,
        max_series: Optional[int] = None,
    ):
        self.resolutions = tuple(
            sorted(resolutions) if resolutions else resolutions_from_env()
        )
        self.max_series = max_series or int(
            os.environ.get("BIOENGINE_TELEM_MAX_SERIES", str(DEFAULT_MAX_SERIES))
        )
        self._series: dict[tuple[str, str], _DeploymentSeries] = {}
        self._hosts: dict[str, float] = {}  # host_id -> last push wall time
        self._lock = threading.Lock()

    # ---- ingest -------------------------------------------------------------

    def ingest(self, snapshot: dict, host_id: Optional[str] = None) -> int:
        """Fold one sampler snapshot in. Returns the number of
        deployment entries accepted (0 for a malformed push — a bad
        peer must never throw into the RPC plane)."""
        if not isinstance(snapshot, dict):
            return 0
        captured_at = float(snapshot.get("captured_at") or time.time())
        deployments = snapshot.get("deployments")
        if not isinstance(deployments, dict):
            return 0
        accepted = 0
        with self._lock:
            if host_id is not None:
                self._hosts[host_id] = captured_at
                if len(self._hosts) > 4 * self.max_series:
                    oldest = min(self._hosts, key=self._hosts.get)
                    self._hosts.pop(oldest, None)
            for key_str, snap in deployments.items():
                if not isinstance(snap, dict):
                    continue
                app, _, dep = str(key_str).partition("/")
                key = (app, dep)
                series = self._series.get(key)
                if series is None:
                    if len(self._series) >= self.max_series:
                        victim = min(
                            self._series, key=lambda k: self._series[k].updated_at
                        )
                        self._series.pop(victim, None)
                    series = self._series[key] = _DeploymentSeries(
                        self.resolutions
                    )
                series.add(captured_at, snap)
                accepted += 1
        return accepted

    def sweep(self, app: str, deployment: Optional[str] = None) -> None:
        """Drop a swept deployment's (or whole app's) series — called by
        undeploy so ``get_telemetry`` never reports a dead deployment
        as live history."""
        with self._lock:
            for key in [
                k
                for k in self._series
                if k[0] == app and (deployment is None or k[1] == deployment)
            ]:
                del self._series[key]

    # ---- read ---------------------------------------------------------------

    def keys(self) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(self._series)

    def hosts(self) -> dict[str, float]:
        with self._lock:
            return dict(self._hosts)

    def series(
        self,
        app: str,
        deployment: str,
        name: str,
        since: Optional[float] = None,
        resolution: Optional[float] = None,
        now: Optional[float] = None,
    ) -> list[dict]:
        """One reconstructed series, oldest first:
        ``[{"t": bucket_start, "value": ...}, ...]`` (None values mean
        the bucket held no relevant samples)."""
        now = now if now is not None else time.time()
        with self._lock:
            s = self._series.get((app, deployment))
            if s is None:
                return []
            step, ring = s.ring_for(since, resolution, now)
            buckets = [b for b in ring if since is None or b.t + step > since]
            out = []
            for b in buckets:
                out.append({"t": b.t, "value": self._value(b, name, step)})
            return out

    @staticmethod
    def _value(b: _Bucket, name: str, step: float) -> Optional[float]:
        if name == "request_rate":
            return round(b.sums.get("requests", 0.0) / step, 6)
        if name == "error_rate":
            return round(b.sums.get("errors", 0.0) / step, 6)
        if name == "shed_rate":
            return round(b.sums.get("shed", 0.0) / step, 6)
        if name == "error_ratio":
            req = b.sums.get("requests", 0.0)
            return round(b.sums.get("errors", 0.0) / req, 6) if req else None
        if name == "chip_seconds":
            return round(b.sums.get("chip_seconds", 0.0), 6)
        if name == "queue_depth":
            return b.gauges.get("queue_depth")
        if name.startswith("latency_p"):
            q = float(name[len("latency_p"):]) / 100.0
            return quantile_from_buckets(
                b.buckets.get("latency_buckets", {}),
                b.sums.get("requests") or None,
                q,
            )
        if name.startswith("replica_latency_p"):
            q = float(name[len("replica_latency_p"):]) / 100.0
            return quantile_from_buckets(
                b.buckets.get("replica_latency_buckets", {}),
                b.sums.get("replica_requests") or None,
                q,
            )
        if name == "tokens_per_second":
            return round(b.sums.get("tokens", 0.0) / step, 6)
        if name.startswith("inter_token_p"):
            q = float(name[len("inter_token_p"):]) / 100.0
            return quantile_from_buckets(
                b.buckets.get("inter_token_buckets", {}),
                b.sums.get("inter_token_count") or None,
                q,
            )
        return None

    def window_aggregate(
        self,
        app: str,
        deployment: str,
        window_s: float,
        now: Optional[float] = None,
    ) -> dict:
        """Totals over ``[now - window_s, now]`` from the finest ring
        that covers the window — the SLO burn-rate input. Keys:
        every _SUM_FIELDS member plus merged ``latency_buckets``."""
        now = now if now is not None else time.time()
        acc: dict[str, Any] = {}
        with self._lock:
            s = self._series.get((app, deployment))
            if s is None:
                return acc
            step, ring = s.ring_for(now - window_s, None, now)
            cut = now - window_s
            for b in ring:
                if b.t + step <= cut:
                    continue
                b.merged_into(acc)
        return acc

    def coverage_s(self) -> float:
        """The longest window this store can actually answer (coarsest
        ring's span) — SLO status reports budget math over
        ``min(slo_window, coverage)`` and flags the truncation."""
        return max(step * slots for step, slots in self.resolutions)

    def describe(self) -> dict:
        """Store sizing facts for status surfaces (and the docs'
        capacity math): resolutions, live keys, pushing hosts."""
        with self._lock:
            return {
                "resolutions": [
                    {"step_s": step, "slots": slots, "span_s": step * slots}
                    for step, slots in self.resolutions
                ],
                "series": len(self._series),
                "max_series": self.max_series,
                "hosts": dict(self._hosts),
            }


# ---------------------------------------------------------------------------
# registry delta sampler
# ---------------------------------------------------------------------------

# family -> (kind of contribution). The controller process carries the
# handle-side families (requests_total / request_e2e_seconds /
# scheduler_rejected_total / serve_queue_depth) plus any local
# replicas' families; a worker-host process carries only the
# replica-side ones. Each process samples what it has — the store sums
# the contributions, and no family appears on both sides of one
# request (chip_seconds accrues exactly where the replica runs).
_OK_OUTCOMES = ("ok",)


class RegistrySampler:
    """Diffs successive ``metrics.collect()`` snapshots into the
    per-deployment delta dict the store ingests. The first call
    establishes the baseline and returns None."""

    def __init__(self, registry=None):
        from bioengine_tpu.utils import flight as _flight
        from bioengine_tpu.utils import metrics as _metrics

        self._registry = registry or _metrics.REGISTRY
        self._last: Optional[dict] = None
        self._last_at: Optional[float] = None
        # process identity (the flight recorder's) stamped on every
        # snapshot: the controller drops pushes that originate from its
        # OWN process (an in-process multi-host harness shares one
        # registry — its own sampler already covers it), the same
        # dedup-by-recorder-identity rule merge_records applies
        self.source_id = _flight.recorder_id()

    def sample(self, now: Optional[float] = None) -> Optional[dict]:
        now = now if now is not None else time.time()
        snap = self._registry.collect()
        prev, self._last = self._last, snap
        prev_at, self._last_at = self._last_at, now
        if prev is None:
            return None
        deployments: dict[str, dict] = {}

        def entry(labels: dict) -> Optional[dict]:
            app = labels.get("app")
            dep = labels.get("deployment")
            if not app or not dep:
                return None
            return deployments.setdefault(f"{app}/{dep}", {})

        # one O(n) index per family instead of a linear _match scan per
        # series — a family near the 1000-child cardinality cap would
        # otherwise make every sample tick quadratic
        prev_index: dict[str, dict] = {}

        def old_series(family: str, labels: dict) -> dict:
            idx = prev_index.get(family)
            if idx is None:
                idx = prev_index[family] = {
                    _label_key(s["labels"]): s
                    for s in (prev or {}).get(family, {}).get("series", [])
                }
            return idx.get(_label_key(labels), {})

        def counter_delta(family: str, into: str, predicate=None) -> None:
            for cur in snap.get(family, {}).get("series", []):
                if predicate is not None and not predicate(cur["labels"]):
                    continue
                e = entry(cur["labels"])
                if e is None:
                    continue
                d = cur.get("value", 0.0) - old_series(
                    family, cur["labels"]
                ).get("value", 0.0)
                if d > 0:
                    e[into] = e.get(into, 0.0) + d

        def histogram_delta(family: str, buckets_into: str, count_into: str, sum_into: Optional[str]) -> None:
            for cur in snap.get(family, {}).get("series", []):
                e = entry(cur["labels"])
                if e is None:
                    continue
                old = old_series(family, cur["labels"])
                dcount = cur.get("count", 0) - old.get("count", 0)
                if dcount <= 0:
                    continue
                e[count_into] = e.get(count_into, 0.0) + dcount
                if sum_into is not None:
                    e[sum_into] = e.get(sum_into, 0.0) + (
                        cur.get("sum", 0.0) - old.get("sum", 0.0)
                    )
                old_b = old.get("buckets", {})
                dst = e.setdefault(buckets_into, {})
                for edge, cum in cur.get("buckets", {}).items():
                    d = cum - old_b.get(edge, 0)
                    if d > 0:
                        dst[edge] = dst.get(edge, 0) + d

        # handle-side (controller process)
        counter_delta("requests_total", "requests")
        counter_delta(
            "requests_total",
            "errors",
            predicate=lambda l: l.get("outcome") not in _OK_OUTCOMES,
        )
        counter_delta("scheduler_rejected_total", "shed")
        histogram_delta(
            "request_e2e_seconds", "latency_buckets", "requests_e2e",
            "latency_sum",
        )
        # the e2e histogram's count IS the request count when the
        # outcome counter is absent in this process; when both exist
        # requests_total wins (it classifies outcomes)
        for e in deployments.values():
            if "requests" not in e and "requests_e2e" in e:
                e["requests"] = e["requests_e2e"]
            e.pop("requests_e2e", None)
        # token streaming (handle-side): generated-token throughput and
        # the inter-token gap histogram the inter_token_ms SLO reads
        counter_delta("tokens_generated_total", "tokens")
        histogram_delta(
            "inter_token_seconds", "inter_token_buckets",
            "inter_token_count", None,
        )
        # replica-side (worker-host process, or local placement)
        counter_delta("chip_seconds_total", "chip_seconds")
        histogram_delta(
            "replica_request_seconds", "replica_latency_buckets",
            "replica_requests", None,
        )
        # queue depth is a scrape-time collector gauge
        for cur in snap.get("serve_queue_depth", {}).get("series", []):
            e = entry(cur["labels"])
            if e is not None:
                e["queue_depth"] = cur.get("value", 0.0)

        # drop entries that saw no movement this interval — a snapshot
        # full of empty dicts is noise on the wire and in the rings
        deployments = {k: v for k, v in deployments.items() if v}
        if not deployments:
            return None
        interval = now - prev_at if prev_at is not None else None
        return {
            "captured_at": now,
            "interval_s": round(interval, 3) if interval else None,
            "source_id": self.source_id,
            "deployments": deployments,
        }


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))
