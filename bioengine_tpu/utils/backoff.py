"""Exponential backoff with full jitter — the one shared implementation.

Used by the serving retry path (controller.DeploymentHandle), the RPC
client's reconnect loop, and the datasets HTTP retry. Full jitter
(delay uniform in [0, min(cap, base * 2**attempt)]) keeps a fleet that
failed together from retrying together (AWS architecture blog's
"Exponential Backoff And Jitter" result — full jitter minimizes total
work AND completion time versus equal or decorrelated jitter).
"""

from __future__ import annotations

import random

# 2**_MAX_EXPONENT * any sane base already exceeds any sane cap; beyond
# it the uncapped product overflows float for large attempt counts
# (0.2 * 2**1075 raises OverflowError) — clamp before multiplying.
_MAX_EXPONENT = 32


def full_jitter_delay(attempt: int, base_s: float, cap_s: float) -> float:
    """Delay before retry ``attempt`` (0-based): uniform in
    [0, min(cap_s, base_s * 2**attempt)]."""
    window = min(cap_s, base_s * (2 ** min(max(attempt, 0), _MAX_EXPONENT)))
    return random.uniform(0.0, window)
