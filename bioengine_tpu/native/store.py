"""ctypes binding for the C++ shared-memory object store.

The native library (native/object_store.cpp) owns all mutation under a
process-shared mutex; this binding maps the same POSIX-shm segment with
``mmap`` so ``get`` returns a **zero-copy memoryview** over the shared
bytes. Pins (refcounts) taken at get-time keep the object from being
LRU-evicted while a view is live — release views promptly or use the
``pinned`` context manager.

The library auto-builds from source with ``make`` on first use (the
worker image ships g++); a pure-Python in-process fallback with the
same API keeps environments without a toolchain working (no sharing
across processes there).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
from contextlib import contextmanager
from pathlib import Path
from typing import Optional

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libbioengine_store.so"
_build_lock = threading.Lock()


class BesStats(ctypes.Structure):
    _fields_ = [
        ("capacity", ctypes.c_uint64),
        ("used_bytes", ctypes.c_uint64),
        ("n_objects", ctypes.c_uint64),
        ("hits", ctypes.c_uint64),
        ("misses", ctypes.c_uint64),
        ("evictions", ctypes.c_uint64),
        ("put_count", ctypes.c_uint64),
    ]


def _ensure_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native library; None if unavailable.

    ``BIOENGINE_STORE_LIB`` overrides the library path without
    triggering a build — how the CI sanitizer job (and the slow test in
    tests/test_native_store.py) points the same binding at the
    ASan/TSan-instrumented build from ``make -C native sanitizers``.
    """
    override = os.environ.get("BIOENGINE_STORE_LIB")
    with _build_lock:
        if override:
            # an explicit override must fail LOUDLY: silently falling
            # back to the pure-Python store would let a sanitizer CI
            # run go green while exercising zero native code
            lib = ctypes.CDLL(override)
            return _bind_abi(lib)
        if not _LIB_PATH.exists():
            if not (_NATIVE_DIR / "Makefile").exists():
                return None
            try:
                subprocess.run(
                    ["make"], cwd=_NATIVE_DIR, check=True,
                    capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError:
            return None
    return _bind_abi(lib)


def _bind_abi(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bes_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
    ]
    lib.bes_create.restype = ctypes.c_int
    lib.bes_create_excl.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
    ]
    lib.bes_create_excl.restype = ctypes.c_int
    lib.bes_clear.argtypes = [ctypes.c_void_p]
    lib.bes_clear.restype = ctypes.c_int
    lib.bes_destroy.argtypes = [ctypes.c_char_p]
    lib.bes_destroy.restype = ctypes.c_int
    lib.bes_open.argtypes = [ctypes.c_char_p]
    lib.bes_open.restype = ctypes.c_void_p
    lib.bes_close.argtypes = [ctypes.c_void_p]
    lib.bes_close.restype = None
    lib.bes_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.bes_put.restype = ctypes.c_int
    lib.bes_get_pin.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.bes_get_pin.restype = ctypes.c_int
    lib.bes_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bes_release.restype = ctypes.c_int
    lib.bes_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bes_contains.restype = ctypes.c_int
    lib.bes_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bes_delete.restype = ctypes.c_int
    lib.bes_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(BesStats)]
    lib.bes_stats.restype = ctypes.c_int
    return lib


_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if not _lib_tried:
        _lib = _ensure_lib()
        _lib_tried = True
    return _lib


def native_available() -> bool:
    return get_lib() is not None


class StoreError(OSError):
    pass


def _check(rc: int, op: str) -> None:
    if rc < 0:
        raise StoreError(-rc, f"{op}: {os.strerror(-rc)}")


class SharedObjectStore:
    """One named shm segment shared by every process on the host.

    ``create``:
      - ``"attach"`` (default): join the existing segment, creating it
        exclusively if absent — the right mode for a host-shared cache
        (a late-starting replica must never wipe the segment; the
        create race resolves to one winner).
      - ``True``: force-(re)initialize, unlinking any existing segment.
      - ``False``: attach only; FileNotFoundError if absent.
    """

    def __init__(
        self,
        name: str = "bioengine-store",
        capacity: int = 256 * 1024 * 1024,
        n_slots: int = 16384,
        create: "bool | str" = "attach",
    ):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(
                "native object store unavailable (no toolchain?) — "
                "use LocalObjectStore"
            )
        self._lib = lib
        self.name = name
        self._bname = name.encode()
        if create is True:
            _check(lib.bes_create(self._bname, capacity, n_slots), "create")
        elif create == "attach":
            rc = lib.bes_create_excl(self._bname, capacity, n_slots)
            if rc not in (0, -17):  # -EEXIST = someone else has it: fine
                _check(rc, "create")
        self._handle = lib.bes_open(self._bname)
        if not self._handle:
            raise FileNotFoundError(f"shm store '{name}' not found")
        # map the segment read-only in Python for zero-copy views
        fd = os.open(f"/dev/shm/{name}", os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            self._map = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        self._closed = False
        # (key, view) pairs whose release raised BufferError because an
        # export (np.frombuffer) was still alive; retried on later calls.
        self._deferred_releases: list = []

    # ---- core API -----------------------------------------------------------

    def put(self, key: str, data) -> None:
        """Copy ``data`` into the arena (LRU-evicting as needed) —
        exactly ONE copy, the memcpy inside ``bes_put``: bytes,
        memoryviews, and C-contiguous ndarrays all hand the native
        layer a raw pointer instead of round-tripping through
        ``bytes()`` first (the RPC shm fast path's one-copy promise
        rests on this). Raises FileExistsError if the key is present."""
        rc = self._put_rc(key, data)
        if rc == -17:  # EEXIST
            raise FileExistsError(key)
        _check(rc, f"put {key!r}")

    def try_put(self, key: str, data) -> bool:
        """``put`` that reports capacity/key pressure instead of
        raising: False when the key exists or the store cannot fit the
        object (full of pinned blocks, or larger than the arena) — the
        transport's cue to fall back to wire frames."""
        rc = self._put_rc(key, data)
        if rc in (-17, -28, -12):  # EEXIST / ENOSPC / ENOMEM
            return False
        _check(rc, f"put {key!r}")
        return True

    def _put_rc(self, key: str, data) -> int:
        import numpy as np

        # np.frombuffer is the one stdlib-adjacent way to borrow a raw
        # pointer from read-only bytes/memoryview without copying
        # (ctypes.from_buffer demands writable memory)
        flat = np.frombuffer(data, dtype=np.uint8)
        ptr = ctypes.c_void_p(flat.ctypes.data if flat.size else None)
        return self._lib.bes_put(
            self._handle, key.encode(), ptr, flat.size
        )

    def get(self, key: str) -> Optional[memoryview]:
        """Zero-copy view of the stored bytes, or None. The view holds a
        pin — call release(key) (or use ``pinned``) when done."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.bes_get_pin(
            self._handle, key.encode(), ctypes.byref(off), ctypes.byref(size)
        )
        if rc == -2:  # ENOENT
            return None
        _check(rc, f"get {key!r}")
        return memoryview(self._map)[off.value : off.value + size.value]

    def release(self, key: str) -> None:
        self._lib.bes_release(self._handle, key.encode())

    @contextmanager
    def pinned(self, key: str):
        """``with store.pinned(k) as view:`` — auto-release.

        If the caller kept an export of the view alive (np.frombuffer),
        ``view.release()`` raises BufferError; the store pin is then
        KEPT (the block must stay unevictable while any export points
        into the mapping) and retried on later calls / close()."""
        self._drain_deferred_releases()
        view = self.get(key)
        try:
            yield view
        finally:
            if view is not None:
                try:
                    view.release()
                except BufferError:
                    # exports alive: keep the pin so eviction can't
                    # recycle bytes under them; retry later
                    self._deferred_releases.append((key, view))
                else:
                    self.release(key)

    def _drain_deferred_releases(self) -> None:
        still_held = []
        for key, view in self._deferred_releases:
            try:
                view.release()
            except BufferError:
                still_held.append((key, view))
            else:
                self.release(key)
        self._deferred_releases = still_held

    def get_bytes(self, key: str) -> Optional[bytes]:
        """Copying read — no pin left behind."""
        with self.pinned(key) as view:
            return None if view is None else bytes(view)

    def contains(self, key: str) -> bool:
        return bool(self._lib.bes_contains(self._handle, key.encode()))

    def delete(self, key: str) -> bool:
        rc = self._lib.bes_delete(self._handle, key.encode())
        if rc == -2:
            return False
        _check(rc, f"delete {key!r}")
        return True

    def clear(self) -> int:
        """Remove every unpinned entry in place — all attached
        processes observe the cleared state. Returns entries removed."""
        rc = self._lib.bes_clear(self._handle)
        _check(rc, "clear")
        return rc

    def stats(self) -> dict:
        st = BesStats()
        _check(self._lib.bes_stats(self._handle, ctypes.byref(st)), "stats")
        return {f: getattr(st, f) for f, _ in BesStats._fields_}

    # ---- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._map.close()
            except BufferError:
                # numpy arrays / memoryviews over the mapping are still
                # alive; the map stays until they're collected. Unpinning
                # already happened, so this only delays address release.
                pass
            self._lib.bes_close(self._handle)
            self._handle = None

    def destroy(self) -> None:
        """Close and unlink the shm segment (unlinks even if live views
        keep the mapping itself alive)."""
        self.close()
        self._lib.bes_destroy(self._bname)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class LocalObjectStore:
    """Same API, plain-Python, single-process — the fallback when no
    native toolchain exists. LRU with byte budget, like ChunkCache."""

    def __init__(
        self,
        name: str = "local",
        capacity: int = 256 * 1024 * 1024,
        n_slots: int = 0,
        create: "bool | str" = "attach",
    ):
        self.name = name
        self.capacity = capacity
        self._data: dict[str, bytes] = {}
        self._order: list[str] = []
        self._used = 0
        self._lock = threading.Lock()
        self._stats = {"hits": 0, "misses": 0, "evictions": 0, "put_count": 0}
        self._deferred_releases: list = []

    def put(self, key: str, data) -> None:
        buf = bytes(data)
        if len(buf) > self.capacity:
            raise StoreError(28, "object larger than store capacity")
        with self._lock:
            if key in self._data:
                raise FileExistsError(key)
            while self._used + len(buf) > self.capacity and self._order:
                old = self._order.pop(0)
                self._used -= len(self._data.pop(old))
                self._stats["evictions"] += 1
            self._data[key] = buf
            self._order.append(key)
            self._used += len(buf)
            self._stats["put_count"] += 1

    def try_put(self, key: str, data) -> bool:
        try:
            self.put(key, data)
        except (FileExistsError, StoreError):
            return False
        return True

    def get(self, key: str) -> Optional[memoryview]:
        with self._lock:
            if key not in self._data:
                self._stats["misses"] += 1
                return None
            self._stats["hits"] += 1
            self._order.remove(key)
            self._order.append(key)
            return memoryview(self._data[key])

    def release(self, key: str) -> None:
        pass

    @contextmanager
    def pinned(self, key: str):
        yield self.get(key)

    def get_bytes(self, key: str) -> Optional[bytes]:
        view = self.get(key)
        return None if view is None else bytes(view)

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def delete(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            self._used -= len(self._data.pop(key))
            self._order.remove(key)
            return True

    def clear(self) -> int:
        with self._lock:
            n = len(self._data)
            self._data.clear()
            self._order.clear()
            self._used = 0
            return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "used_bytes": self._used,
                "n_objects": len(self._data),
                **self._stats,
            }

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        with self._lock:
            self._data.clear()
            self._order.clear()
            self._used = 0


def open_store(
    name: str = "bioengine-store",
    capacity: int = 256 * 1024 * 1024,
    n_slots: int = 16384,
    create: "bool | str" = "attach",
):
    """SharedObjectStore when the native lib is available, else the
    in-process fallback."""
    if native_available():
        return SharedObjectStore(name, capacity, n_slots, create=create)
    return LocalObjectStore(name, capacity, n_slots, create=create)
