"""Native (C++) runtime components and their ctypes bindings.

The reference delegates its node-local runtime to Ray's C++ core
(GCS / raylet / plasma, SURVEY.md §2.1 #4); the pieces the TPU
framework needs natively live here, built from ``native/`` at the repo
root with plain ``make``.
"""

from bioengine_tpu.native.store import (
    LocalObjectStore,
    SharedObjectStore,
    StoreError,
    native_available,
    open_store,
)

__all__ = [
    "LocalObjectStore",
    "SharedObjectStore",
    "StoreError",
    "native_available",
    "open_store",
]
