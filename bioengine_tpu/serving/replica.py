"""Replica — one health-checked instance of an app deployment pinned to
a device set.

The reference's unit is a Ray Serve replica actor wrapped by AppBuilder:
``__init__`` registers the replica, ``async_init`` does async setup,
``test_deployment`` runs once in the background, ``check_health``
orchestrates init -> test -> datasets ping -> user health check
(ref bioengine/apps/builder.py:532-890). This class reproduces that
lifecycle chain without Ray: the instance is a plain Python object
constructed from the app build, pinned to chips accounted in
ClusterState, driven by the controller's health loop.

Scaling stays XLA-friendly: a replica owns a FIXED device set for its
whole life, so its compiled programs never re-shard (SURVEY.md §7
"Replica elasticity vs. XLA's static world" — scale in units of whole
replicas).
"""

from __future__ import annotations

import asyncio
import enum
import os
import time
import traceback
import uuid
from typing import Any, Callable, Optional

from bioengine_tpu.serving.errors import ReplicaUnavailableError
from bioengine_tpu.utils import flight, metrics, tracing
from bioengine_tpu.utils.logger import create_logger

DEFAULT_DRAIN_TIMEOUT_S = float(
    os.environ.get("BIOENGINE_DRAIN_TIMEOUT_S", "30")
)

# per-replica request telemetry: the counter REPLACES the old private
# _total_requests int (describe() reads it back — one bookkeeper), the
# histograms are what GET /metrics serves labeled by deployment+replica
REPLICA_REQUESTS = metrics.counter(
    "replica_requests_total",
    "requests executed by a replica instance",
    ("app", "deployment", "replica"),
)
REPLICA_LATENCY = metrics.histogram(
    "replica_request_seconds",
    "instance method execution time on the replica (post-semaphore)",
    ("app", "deployment", "replica"),
)
REPLICA_PARK = metrics.histogram(
    "replica_park_seconds",
    "time a call waited on the replica's request semaphore",
    ("app", "deployment", "replica"),
)
# the cost feature the future scheduler consumes (ROADMAP item 1):
# device-seconds per request = engine wall seconds x mesh width,
# accumulated HOST-side where the replica executes (utils/tracing.py
# chip accumulator; engines feed it from predict). Always on — this is
# accounting, not optional telemetry.
CHIP_SECONDS = metrics.counter(
    "chip_seconds_total",
    "device-seconds consumed serving requests (engine wall time x mesh width)",
    ("app", "deployment", "method"),
)


class ReplicaState(str, enum.Enum):
    STARTING = "STARTING"
    INITIALIZING = "INITIALIZING"
    TESTING = "TESTING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    # gray failure: alive and passing health checks but a latency
    # outlier vs its deployment siblings (serving/outlier.py). Routable
    # — the replica CAN serve — but the router/scheduler soft-eject it
    # from the scored pick, sending only a trickle of probe traffic
    # until its latency recovers. Assigned controller-side (like
    # breaker ejections); health checks preserve it, latency evidence
    # clears it.
    PROBATION = "PROBATION"
    DRAINING = "DRAINING"          # no new calls; in-flight may finish
    STOPPED = "STOPPED"

# states a replica will EXECUTE new calls in (PROBATION serves probe /
# last-resort traffic — slow is not dead); the router and scheduler
# additionally skip PROBATION in their scored picks
ROUTABLE_STATES = (
    ReplicaState.HEALTHY,
    ReplicaState.TESTING,
    ReplicaState.PROBATION,
)


class ReplicaStateMixin:
    """``state`` as a flight-recorded property: every lifecycle
    transition (including ones assigned from the controller — breaker
    ejections, drains) lands in the postmortem ring with from/to and
    the replica's identity. Shared by :class:`Replica` and
    :class:`bioengine_tpu.serving.remote.RemoteReplica` so local and
    remote replicas leave the same evidence trail."""

    _state: Optional[ReplicaState] = None

    @property
    def state(self) -> ReplicaState:
        return self._state

    @state.setter
    def state(self, value: ReplicaState) -> None:
        old = self._state
        self._state = value
        if old is None or old == value:
            return
        flight.record(
            "replica.state",
            replica=getattr(self, "replica_id", "?"),
            app=getattr(self, "app_id", "?"),
            deployment=getattr(self, "deployment_name", "?"),
            host=getattr(self, "host_id", None),
            **{"from": old.value, "to": value.value},
        )


class Replica(ReplicaStateMixin):
    def __init__(
        self,
        app_id: str,
        deployment_name: str,
        instance_factory: Callable[[], Any],
        device_ids: Optional[list[int]] = None,
        max_ongoing_requests: int = 10,
        log_sink: Optional[Callable[[str, str], None]] = None,
        drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
        batch_config: Optional[dict] = None,
        mesh_shard: Optional[dict] = None,
    ):
        self.app_id = app_id
        self.deployment_name = deployment_name
        self.replica_id = f"{deployment_name}-{uuid.uuid4().hex[:8]}"
        self.device_ids = device_ids or []
        self.state = ReplicaState.STARTING
        self.max_ongoing_requests = max_ongoing_requests
        self.drain_timeout_s = drain_timeout_s
        self.batch_config = dict(batch_config) if batch_config else None
        self.mesh_shard = dict(mesh_shard) if mesh_shard else None
        self._instance_factory = instance_factory
        self.instance: Any = None
        self._semaphore = asyncio.Semaphore(max_ongoing_requests)
        self._ongoing = 0
        self._queued = 0          # callers parked on the semaphore
        self._idle_event = asyncio.Event()
        self._idle_event.set()
        # label children bind in start(): worker_host reassigns
        # replica_id between construction and start, and the metric
        # identity must match the controller's
        self._requests_total: Optional[metrics.CounterChild] = None
        self._m_latency: Optional[metrics.HistogramChild] = None
        self._m_park: Optional[metrics.HistogramChild] = None
        # chip-seconds accounting: per-method counter children (labels
        # resolved once) + a replica-lifetime total describe() reads
        self._m_chip: dict[str, metrics.CounterChild] = {}
        self._chip_seconds = 0.0
        self._test_task: Optional[asyncio.Task] = None
        self._test_error: Optional[str] = None
        self._init_done = False
        self.started_at = time.time()
        self._started_mono = time.monotonic()
        # time-to-first-request breakdown — the number the whole
        # cold-start machinery (compile tier, streamed weights, warm
        # pool) exists to shrink. ttfr_seconds is construction -> first
        # COMPLETED request; init_seconds is the instance build +
        # async_init slice of it. Promoted warm-pool standbys re-anchor
        # at promotion (promote -> first request is the span that
        # matters to the autoscaler).
        self.ttfr: dict[str, Any] = {}
        self.promoted_from_warm_pool = False
        self._first_request_done = False
        self.last_error: Optional[str] = None
        self._log_sink = log_sink
        self.logger = create_logger(f"replica.{self.replica_id}", log_file="off")

    def _log(self, line: str) -> None:
        self.logger.info(line)
        if self._log_sink:
            self._log_sink(self.replica_id, line)

    # ---- lifecycle chain ----------------------------------------------------

    async def start(self) -> None:
        """Construct the instance and run async_init; schedule the
        one-shot background test (the reference runs test_deployment in
        the background and only reports healthy after it passes,
        ref builder.py:739-890)."""
        try:
            self.state = ReplicaState.INITIALIZING
            labels = (self.app_id, self.deployment_name, self.replica_id)
            self._requests_total = REPLICA_REQUESTS.labels(*labels)
            self._m_latency = REPLICA_LATENCY.labels(*labels)
            self._m_park = REPLICA_PARK.labels(*labels)
            self._log("constructing deployment instance")
            self.instance = self._instance_factory()
            if self.device_ids:
                # hand the leased chip group to the instance BEFORE
                # async_init so mesh-aware deployments (model-runner's
                # RuntimeDeployment) can build their device mesh over
                # exactly the chips this replica owns instead of
                # defaulting to jax.devices()[0]
                try:
                    self.instance.bioengine_device_ids = list(self.device_ids)
                except Exception as e:  # noqa: BLE001 — slots/frozen instances opt out
                    # not fatal (the instance may not be mesh-aware), but
                    # a K-chip lease that can't reach the instance means
                    # K-1 idle chips — make that diagnosable
                    self._log(
                        "could not inject device lease "
                        f"{list(self.device_ids)} into instance ({e}); "
                        "replica will run single-device"
                    )
            if self.batch_config:
                # operator-tuned batching knobs from the deployment
                # spec/manifest, injected BEFORE async_init (same
                # contract as the device lease) so instances that build
                # a ContinuousBatcher there pick them up instead of
                # their constructor defaults
                try:
                    self.instance.bioengine_batch_config = dict(
                        self.batch_config
                    )
                except Exception as e:  # noqa: BLE001 — slots/frozen instances opt out
                    self._log(
                        f"could not inject batch config "
                        f"{self.batch_config} into instance ({e})"
                    )
            if self.mesh_shard:
                # cross-host mesh placement (serving/mesh_plan.py): tell
                # the instance WHICH slice of the model this replica
                # holds ({stage, n_stages, kind, axes}) before
                # async_init — same injection contract as the device
                # lease, so a shard builds only its stage's engine and
                # params over its own chips
                try:
                    self.instance.bioengine_mesh_shard = dict(
                        self.mesh_shard
                    )
                except Exception as e:  # noqa: BLE001 — slots/frozen instances opt out
                    self._log(
                        f"could not inject mesh shard {self.mesh_shard} "
                        f"into instance ({e}); replica will build the "
                        f"full model"
                    )
            if hasattr(self.instance, "async_init"):
                await _maybe_await(self.instance.async_init())
            self._init_done = True
            self.ttfr["init_seconds"] = round(
                time.monotonic() - self._started_mono, 4
            )
            if hasattr(self.instance, "test_deployment"):
                self.state = ReplicaState.TESTING
                self._test_task = asyncio.create_task(self._run_test())
            else:
                self.state = ReplicaState.HEALTHY
            self._log(f"replica started (state={self.state})")
        except Exception as e:
            self.last_error = "".join(traceback.format_exception(e))[-2000:]
            self.state = ReplicaState.UNHEALTHY
            self._log(f"replica start failed: {e}")
            flight.record(
                "replica.error",
                severity="error",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                phase="start",
                error=str(e)[:500],
            )
            flight.dump("replica_error", replica=self.replica_id)
            raise

    async def _run_test(self) -> None:
        try:
            self._log("running test_deployment")
            await _maybe_await(self.instance.test_deployment())
            self.state = ReplicaState.HEALTHY
            self._log("test_deployment passed")
        except Exception as e:
            self._test_error = "".join(traceback.format_exception(e))[-2000:]
            self.state = ReplicaState.UNHEALTHY
            self.last_error = self._test_error
            self._log(f"test_deployment failed: {e}")
            flight.record(
                "replica.error",
                severity="error",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                phase="test_deployment",
                error=str(e)[:500],
            )
            flight.dump("replica_error", replica=self.replica_id)

    async def check_health(self) -> ReplicaState:
        """init done -> test passed -> user check_health."""
        if self.state in (
            ReplicaState.STOPPED,
            ReplicaState.UNHEALTHY,
            ReplicaState.DRAINING,
        ):
            return self.state
        if not self._init_done:
            return self.state
        if self._test_task and not self._test_task.done():
            return self.state  # still TESTING
        if self._test_error:
            return ReplicaState.UNHEALTHY
        if hasattr(self.instance, "check_health"):
            try:
                await _maybe_await(self.instance.check_health())
                # gray failure is INVISIBLE to health checks by
                # definition — a passing check must not clear a
                # controller-assigned PROBATION; only latency evidence
                # from probe traffic does (serving/outlier.py)
                if self.state != ReplicaState.PROBATION:
                    self.state = ReplicaState.HEALTHY
            except Exception as e:
                self.last_error = str(e)
                self.state = ReplicaState.UNHEALTHY
                self._log(f"user check_health failed: {e}")
        return self.state

    async def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Reject new calls, let in-flight requests finish (bounded).
        Returns True when the replica is idle, False on timeout with
        requests still running (the caller stops it anyway)."""
        if self.state in (
            ReplicaState.HEALTHY,
            ReplicaState.TESTING,
            ReplicaState.PROBATION,
            ReplicaState.INITIALIZING,
        ):
            self.state = ReplicaState.DRAINING
            self._log(f"draining ({self._ongoing} in-flight)")
            flight.record(
                "replica.drain",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                in_flight=self._ongoing,
            )
        if self._ongoing == 0:
            return True
        timeout = self.drain_timeout_s if timeout_s is None else timeout_s
        try:
            await asyncio.wait_for(self._idle_event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            self._log(
                f"drain timed out after {timeout}s "
                f"({self._ongoing} requests stranded)"
            )
            flight.record(
                "replica.drain",
                severity="warning",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                timed_out=True,
                stranded=self._ongoing,
            )
            return False

    async def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        # graceful path: a routable replica drains before it stops, so
        # undeploy/autoscale-down never strand in-flight requests
        if self.state in (
            ReplicaState.HEALTHY,
            ReplicaState.TESTING,
            ReplicaState.PROBATION,
            ReplicaState.DRAINING,
        ):
            await self.drain(drain_timeout_s)
        self.state = ReplicaState.STOPPED
        if self._test_task:
            self._test_task.cancel()
        if self.instance is not None and hasattr(self.instance, "close"):
            try:
                await _maybe_await(self.instance.close())
            except Exception as e:
                self._log(f"close() raised: {e}")
        self._log("replica stopped")

    # ---- request path -------------------------------------------------------

    async def call(self, method: str, *args, **kwargs) -> Any:
        """Invoke a method on the instance under the request semaphore.
        Semaphore occupancy IS the load signal (the reference had to fake
        HTTP traffic so Ray Serve's autoscaler could see WebRTC load,
        ref apps/proxy_deployment.py:405-442 — here the controller reads
        ``load`` directly)."""
        # TESTING is routable: init completed, the one-shot background
        # test is still running — same window in which the reference's
        # Serve replicas already accept handle calls (ref builder.py:739-811)
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} not healthy ({self.state})"
            )
        fn = getattr(self.instance, method, None)
        if fn is None:
            raise AttributeError(
                f"{self.deployment_name} has no method '{method}'"
            )
        m_on = metrics.metrics_enabled()
        self._queued += 1
        t_park = time.monotonic()
        try:
            with tracing.trace_span("replica.park", replica=self.replica_id):
                await self._semaphore.acquire()
        finally:
            self._queued -= 1
        if m_on and self._m_park is not None:
            self._m_park.observe(time.monotonic() - t_park)
        try:
            # re-check after the (possibly long) semaphore wait: a drain
            # or stop that happened while this call was parked must not
            # let it execute against a torn-down instance — the typed
            # rejection makes the router fail it over instead
            if self.state not in ROUTABLE_STATES:
                raise ReplicaUnavailableError(
                    f"replica {self.replica_id} not healthy ({self.state})"
                )
            self._ongoing += 1
            self._idle_event.clear()
            if self._requests_total is not None:
                self._requests_total.inc()
            first = not self._first_request_done
            t_exec = time.monotonic()
            # chip-seconds accumulate here, where app/deployment/method
            # labels exist: engines called (directly or through the
            # batcher/dispatch thread) add wall x mesh-width into the
            # request-scoped accumulator. Batched flushes attribute the
            # whole batch's device time to the submitter whose context
            # the flush task inherited — totals stay exact, per-method
            # attribution amortizes across co-batched requests.
            acc, cs_token = tracing.start_chip_accounting()
            try:
                with tracing.trace_span(
                    "replica.execute",
                    replica=self.replica_id,
                    method=method,
                ):
                    result = await _maybe_await(fn(*args, **kwargs))
                if first and not self._first_request_done:
                    self._first_request_done = True
                    now = time.monotonic()
                    self.ttfr["first_request_seconds"] = round(
                        now - t_exec, 4
                    )
                    self.ttfr["ttfr_seconds"] = round(
                        now - self._started_mono, 4
                    )
                    # the closing event of the scale-up→first-request
                    # flight timeline (replica.place / warmpool.promote
                    # opened it, program.compile sits in between)
                    flight.record(
                        "replica.first_request",
                        replica=self.replica_id,
                        app=self.app_id,
                        deployment=self.deployment_name,
                        method=method,
                        ttfr_seconds=self.ttfr["ttfr_seconds"],
                        warm_pool=self.promoted_from_warm_pool,
                    )
                return result
            finally:
                tracing.stop_chip_accounting(cs_token)
                if acc.seconds > 0.0:
                    self._chip_seconds += acc.seconds
                    child = self._m_chip.get(method)
                    if child is None:
                        child = self._m_chip[method] = CHIP_SECONDS.labels(
                            self.app_id, self.deployment_name, method
                        )
                    child.inc(acc.seconds)
                if m_on and self._m_latency is not None:
                    self._m_latency.observe(time.monotonic() - t_exec)
                self._ongoing -= 1
                if self._ongoing == 0:
                    self._idle_event.set()
        finally:
            self._semaphore.release()

    async def call_stream(self, method: str, *args, **kwargs):
        """Streaming twin of :meth:`call`: the instance method returns
        an async iterator (a generate-style endpoint backed by
        ``serving/decode.py``) and items are yielded to the caller as
        they are produced. The semaphore slot is held for the WHOLE
        stream — an in-flight generation occupies replica capacity
        exactly like a unary call, so ``load`` and the autoscaler see
        it — and chip-seconds accounting closes when the stream does
        (the decode loop books fair-share device time into the
        request-scoped accumulator per emitted token)."""
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} not healthy ({self.state})"
            )
        fn = getattr(self.instance, method, None)
        if fn is None:
            raise AttributeError(
                f"{self.deployment_name} has no method '{method}'"
            )
        m_on = metrics.metrics_enabled()
        self._queued += 1
        t_park = time.monotonic()
        try:
            with tracing.trace_span("replica.park", replica=self.replica_id):
                await self._semaphore.acquire()
        finally:
            self._queued -= 1
        if m_on and self._m_park is not None:
            self._m_park.observe(time.monotonic() - t_park)
        try:
            if self.state not in ROUTABLE_STATES:
                raise ReplicaUnavailableError(
                    f"replica {self.replica_id} not healthy ({self.state})"
                )
            self._ongoing += 1
            self._idle_event.clear()
            if self._requests_total is not None:
                self._requests_total.inc()
            t_exec = time.monotonic()
            acc, cs_token = tracing.start_chip_accounting()
            try:
                with tracing.trace_span(
                    "replica.stream",
                    replica=self.replica_id,
                    method=method,
                ):
                    result = await _maybe_await(fn(*args, **kwargs))
                    if hasattr(result, "__aiter__"):
                        async for item in result:
                            yield item
                    else:
                        # unary method called through the stream path:
                        # a one-item stream keeps the envelope uniform
                        yield result
                if not self._first_request_done:
                    self._first_request_done = True
                    now = time.monotonic()
                    self.ttfr["first_request_seconds"] = round(
                        now - t_exec, 4
                    )
                    self.ttfr["ttfr_seconds"] = round(
                        now - self._started_mono, 4
                    )
                    flight.record(
                        "replica.first_request",
                        replica=self.replica_id,
                        app=self.app_id,
                        deployment=self.deployment_name,
                        method=method,
                        ttfr_seconds=self.ttfr["ttfr_seconds"],
                        warm_pool=self.promoted_from_warm_pool,
                    )
            finally:
                tracing.stop_chip_accounting(cs_token)
                if acc.seconds > 0.0:
                    self._chip_seconds += acc.seconds
                    child = self._m_chip.get(method)
                    if child is None:
                        child = self._m_chip[method] = CHIP_SECONDS.labels(
                            self.app_id, self.deployment_name, method
                        )
                    child.inc(acc.seconds)
                if m_on and self._m_latency is not None:
                    self._m_latency.observe(time.monotonic() - t_exec)
                self._ongoing -= 1
                if self._ongoing == 0:
                    self._idle_event.set()
        finally:
            self._semaphore.release()

    async def call_bounded(
        self,
        method: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Any:
        """``call`` with a per-attempt time budget (the request path's
        entry point — a kwarg-free envelope so app methods may use any
        parameter names)."""
        coro = self.call(method, *args, **(kwargs or {}))
        if timeout_s is None:
            return await coro
        return await asyncio.wait_for(coro, timeout_s)

    async def call_batch(
        self,
        method: str,
        requests: list,
        timeout_s: Optional[float] = None,
        wire: bool = False,
    ) -> list:
        """Execute a controller-coalesced group of compatible calls.
        Each member runs the NORMAL per-call path (semaphore slot,
        routability re-check, metrics, chip accounting) concurrently —
        so all K land in the same event-loop window and an instance
        with its own ``ContinuousBatcher`` merges them into one forward
        — while per-member failures stay isolated: one member's
        exception never poisons its groupmates. Returns one envelope
        per request, in order: ``{"ok": True, "result": ...}`` or a
        failure carrying the real exception object (in-process path) /
        its type name + message (``wire=True``, the ``__batch__`` RPC
        verb — the same type-name contract RemoteError classification
        already rides)."""

        async def one(r: dict) -> dict:
            try:
                result = await self.call(
                    method, *(r.get("args") or ()), **(r.get("kwargs") or {})
                )
                return {"ok": True, "result": result}
            except Exception as e:  # noqa: BLE001 — per-member isolation is the point
                if wire:
                    return {
                        "ok": False,
                        "type": type(e).__name__,
                        "error": str(e),
                    }
                return {"ok": False, "exception": e}

        gathered = asyncio.gather(*(one(r) for r in requests))
        if timeout_s is None:
            return await gathered
        return await asyncio.wait_for(gathered, timeout_s)

    def mark_promoted(self) -> None:
        """Warm-pool standby → serving replica: re-anchor the TTFR
        clock at promotion (the pool already paid init/compile/load;
        the span an operator cares about is promote → first request)."""
        self.promoted_from_warm_pool = True
        self.ttfr["standby_seconds"] = round(
            time.monotonic() - self._started_mono, 4
        )
        self._started_mono = time.monotonic()
        self._first_request_done = False

    @property
    def load(self) -> float:
        return self._ongoing / max(1, self.max_ongoing_requests)

    def describe(self) -> dict:
        d = {
            "replica_id": self.replica_id,
            "deployment": self.deployment_name,
            "state": self.state.value,
            "device_ids": self.device_ids,
            "ongoing_requests": self._ongoing,
            "queued_requests": self._queued,
            # backed by the process-wide metrics registry (same counter
            # GET /metrics serves) — describe() is a reader, not a
            # second bookkeeper
            "total_requests": (
                int(self._requests_total.value)
                if self._requests_total is not None
                else 0
            ),
            "load": self.load,
            # device-seconds this replica's requests consumed (engine
            # wall x mesh width) — the per-replica slice of the
            # chip_seconds_total{app,deployment,method} counter
            "chip_seconds_total": round(self._chip_seconds, 6),
            # monotonic, not wall — an NTP step must not age a replica
            "uptime_seconds": time.monotonic() - self._started_mono,
            "last_error": self.last_error,
        }
        # cold-start surface: the replica-level TTFR breakdown plus the
        # per-pipeline weights/compile detail from deployments that
        # expose ``cold_start_info()`` (model-runner's RuntimeDeployment)
        cold: dict = dict(self.ttfr)
        cold["promoted_from_warm_pool"] = self.promoted_from_warm_pool
        cs_fn = getattr(self.instance, "cold_start_info", None)
        if callable(cs_fn):
            try:
                cold["pipelines"] = cs_fn()
            except Exception as e:  # noqa: BLE001 — stats never break health
                cold["pipelines"] = {"error": str(e)}
        d["cold_start"] = cold
        # deployments that run the overlapped inference pipeline expose
        # a sync ``pipeline_stats()`` (e.g. model-runner's
        # RuntimeDeployment); surface it so the controller's
        # get_app_status shows cut/put/compute/readback/stitch seconds
        # and overlap efficiency per replica
        stats_fn = getattr(self.instance, "pipeline_stats", None)
        if callable(stats_fn):
            try:
                d["pipeline_stats"] = stats_fn()
            except Exception as e:  # noqa: BLE001 — stats never break health
                d["pipeline_stats"] = {"error": str(e)}
        # mesh-aware deployments report how their leased chip group is
        # actually used (mesh shape + per-chip utilization) so the
        # controller can see sharding health, not just chip accounting
        mesh_fn = getattr(self.instance, "mesh_info", None)
        if callable(mesh_fn):
            try:
                d["mesh"] = mesh_fn()
            except Exception as e:  # noqa: BLE001 — stats never break health
                d["mesh"] = {"error": str(e)}
        # deployments that hold their own control-plane connection
        # (data proxies, federated apps) expose ``rpc_stats()`` — the
        # transport counters ride the same describe path so
        # get_app_status shows per-replica bytes moved and shm hit-rate
        rpc_fn = getattr(self.instance, "rpc_stats", None)
        if callable(rpc_fn):
            try:
                d["rpc_stats"] = rpc_fn()
            except Exception as e:  # noqa: BLE001 — stats never break health
                d["rpc_stats"] = {"error": str(e)}
        return d


async def _maybe_await(value):
    if asyncio.iscoroutine(value):
        return await value
    return value
