"""ServeController — deployment orchestration, health loop, autoscaling.

Replaces Ray Serve as used by the reference (serve.run per app with
autoscaling 1-10 replicas and health-check-driven restarts, ref
bioengine/apps/proxy_deployment.py:25-47, bioengine/apps/manager.py:
355-455). Differences by design:

- Load is measured at the controller (per-replica semaphore occupancy +
  queue depth), so the reference's "mimic request" workaround for the
  Serve autoscaler (proxy_deployment.py:405-442) has no equivalent —
  the signal is native.
- Replicas scale in whole units, each owning a fixed chip set leased
  from ClusterState; unplaceable replicas enqueue a pending workload,
  which is exactly what drives the provisioner's scale-up
  (cluster/provisioner.py check_scaling).
"""

from __future__ import annotations

import asyncio
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.rpc.protocol import PROTO_EPOCH1, PROTO_MESH1
from bioengine_tpu.serving.mesh_plan import (
    MeshConfig,
    MeshPlanError,
    plan_mesh,
)
from bioengine_tpu.serving.outlier import OutlierConfig
from bioengine_tpu.serving.mesh_replica import MeshReplica
from bioengine_tpu.serving.remote import RemoteReplica
from bioengine_tpu.serving.scheduler import (
    DeploymentScheduler,
    SchedulingConfig,
)
from bioengine_tpu.serving.replica import (
    CHIP_SECONDS,
    ROUTABLE_STATES,
    Replica,
    ReplicaState,
)
from bioengine_tpu.serving.router import (
    BREAKER_TRIPS,
    REQUEST_E2E,
    REQUEST_FAILOVERS,
    REQUEST_HEDGES,
    REQUEST_OUTCOMES,
    ROUTE_WAIT,
    DeploymentHandle,
    RequestOptions,
    RouterCore,
    RoutingTablePublisher,
    _min_defined,
)
from bioengine_tpu.serving.slo import SLOConfig, SLOEngine
from bioengine_tpu.serving.compile_tier import CompileCacheTier
from bioengine_tpu.serving.journal import (
    ControlJournal,
    spec_from_dict,
    spec_to_dict,
)
from bioengine_tpu.serving.warm_pool import WarmPool, WarmPoolConfig
from bioengine_tpu.utils import flight, metrics, tracing
from bioengine_tpu.utils.tasks import spawn_supervised
from bioengine_tpu.utils.telemetry import (
    SERIES_NAMES,
    RegistrySampler,
    TelemetryStore,
)
from bioengine_tpu.utils.logger import create_logger

# The request-path metric families (REQUEST_E2E, REQUEST_OUTCOMES,
# REQUEST_FAILOVERS, ROUTE_WAIT, BREAKER_TRIPS, REQUEST_HEDGES) moved to
# serving/router.py with the request path itself; they are re-imported
# above so existing `controller.REQUEST_*` references keep resolving.

# durable control plane (serving/journal.py): the fencing epoch this
# process serves under, and what the recovery reconcile did
CONTROLLER_EPOCH = metrics.gauge(
    "controller_epoch",
    "monotonic fencing epoch minted at controller start (journaled)",
)
RECONCILE_ADOPTED = metrics.counter(
    "reconcile_adopted_total",
    "replicas re-adopted in place from host inventory at recovery",
)
RECONCILE_REPLACED = metrics.counter(
    "reconcile_replaced_total",
    "replicas re-placed from journaled intent at recovery settle",
)
RECONCILE_DROPPED = metrics.counter(
    "reconcile_dropped_total",
    "host-reported replicas dropped at recovery (no matching intent)",
)

# host verbs that carry the controller epoch so hosts can fence a
# wedged-then-revived old controller (register_host carries it in its
# RESULT instead — the host learns the epoch there)
_EPOCH_STAMPED_VERBS = frozenset(
    {"start_replica", "drain_replica", "stop_replica"}
)


def _collect_controllers(instances: list) -> list:
    """Scrape-time gauges from live controllers: router queue depth,
    replica states, and chip-lease occupancy — the load features the
    autoscaler/scheduler consumes, now exported instead of thrown
    away after each health tick. Values aggregate across controllers
    (tests build several per process; one Prometheus series per label
    set must stay unique)."""
    depth_by_key: dict[tuple, int] = {}
    replicas_by_key: dict[tuple, int] = {}
    breaker_open = 0
    chips_total = 0
    chips_free = 0
    for c in instances:
        for (app_id, dep), depth in list(c._queue_depth.items()):
            key = (app_id, dep)
            depth_by_key[key] = depth_by_key.get(key, 0) + depth
        for app in list(c.apps.values()):
            for dep_name, replicas in list(app.replicas.items()):
                for r in list(replicas):
                    key = (app.app_id, dep_name, r.state.value)
                    replicas_by_key[key] = replicas_by_key.get(key, 0) + 1
        breaker_open += len(c._breaker_counts)
        chips_total += c.cluster_state.topology.n_chips
        chips_free += c.cluster_state.free_chips()
    out = [
        metrics.Sample(
            "serve_queue_depth",
            depth,
            {"app": app_id, "deployment": dep},
            help="requests currently inside DeploymentHandle.call",
        )
        for (app_id, dep), depth in depth_by_key.items()
    ]
    out.extend(
        metrics.Sample(
            "serve_replicas",
            n,
            {"app": app_id, "deployment": dep, "state": state},
            help="replicas by lifecycle state",
        )
        for (app_id, dep, state), n in replicas_by_key.items()
    )
    out.append(
        metrics.Sample(
            "breaker_open_replicas",
            breaker_open,
            help="replicas with a non-zero consecutive transport-failure count",
        )
    )
    out.append(
        metrics.Sample("chips_total", chips_total, help="chips on local hosts")
    )
    out.append(
        metrics.Sample(
            "chips_free", chips_free, help="unleased chips on local hosts"
        )
    )
    return out


_CONTROLLERS = metrics.InstanceSet("serve_controller", _collect_controllers)


@dataclass
class DeploymentSpec:
    name: str
    instance_factory: Callable[[], Any]
    num_replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 3
    chips_per_replica: int = 0
    max_ongoing_requests: int = 10
    autoscale: bool = True
    target_load: float = 0.7          # scale up above, down below half
    # artifact payload (manifest + sources + kwargs) for building this
    # deployment on a REMOTE worker host — set by AppBuilder; None means
    # the deployment can only be placed locally
    remote_payload: Optional[dict] = None
    # replica-side ContinuousBatcher knobs, surfaced from the manifest
    # (deployment_config.<dep>.batching) and injected into the instance
    # as ``bioengine_batch_config`` before async_init; None keeps the
    # instance's own defaults
    max_batch: Optional[int] = None
    max_wait_ms: Optional[float] = None
    # opt-in global scheduler (cross-replica batching + admission
    # control + predictive autoscaling); None keeps the per-request
    # router path
    scheduling: Optional[SchedulingConfig] = None
    # per-deployment service objectives (manifest slo: block) — the
    # controller's SLO engine evaluates burn rates against these; None
    # means untracked (no alerting, no budget accounting)
    slo: Optional[SLOConfig] = None
    # controller-managed standby replicas (manifest warm_pool: block):
    # pre-started out-of-rotation replicas that absorb scale-up and
    # preemption by PROMOTION instead of a cold start; None = no pool
    warm_pool: Optional[WarmPoolConfig] = None
    # multi-host mesh placement (manifest mesh: block): one logical
    # replica whose pipeline/dp/tp shards span several hosts' chip
    # leases (serving/mesh_plan.py) — the path for checkpoints bigger
    # than any single host's lease; None = single-host replicas
    mesh: Optional[MeshConfig] = None

    def batch_config(self) -> Optional[dict]:
        if self.max_batch is None and self.max_wait_ms is None:
            return None
        out: dict = {}
        if self.max_batch is not None:
            out["max_batch"] = int(self.max_batch)
        if self.max_wait_ms is not None:
            out["max_wait_ms"] = float(self.max_wait_ms)
        return out


@dataclass
class AppDeployment:
    app_id: str
    specs: dict[str, DeploymentSpec]
    replicas: dict[str, list[Replica]] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)
    status: str = "DEPLOYING"         # DEPLOYING | RUNNING | UNHEALTHY | DEPLOY_FAILED | STOPPED
    # per-method ACL for cross-host route_call — same shape as the app
    # proxy's authorized_users (list = all methods, dict = per-method).
    # None means "no ACL recorded": route_call then admits admins only.
    acl: Any = None


class ServeController(RouterCore):
    def __init__(
        self,
        cluster_state: Optional[ClusterState] = None,
        health_check_period: float = 10.0,
        log_file: Optional[str] = None,
        breaker_threshold: Optional[int] = None,
        health_check_concurrency: int = 8,
        outlier_config: Optional[OutlierConfig] = None,
        control_dir: Optional[str] = None,
    ):
        self.cluster_state = cluster_state or ClusterState()
        self.health_check_period = health_check_period
        self.health_check_concurrency = health_check_concurrency
        self.apps: dict[str, AppDeployment] = {}
        self.logger = create_logger("serving", log_file=log_file)
        self._health_task: Optional[asyncio.Task] = None
        # the whole request path — breaker, outlier probation, replica
        # pick/wait, rr counters, queue depth, scheduler registry —
        # comes from RouterCore (serving/router.py), shared verbatim
        # with the standalone router tier
        self._init_router_core(
            breaker_threshold=breaker_threshold,
            outlier_config=outlier_config,
        )
        # versioned routing-table publication for the scale-out router
        # tier (served over serve-router.get_routing_table)
        self.router_publisher = RoutingTablePublisher(self)
        # warm pools, one per deployment that opted in via
        # DeploymentSpec.warm_pool; standbys live here, OUT of the
        # routing set, until a scale-up/preemption promotes them
        self._warm_pools: dict[tuple[str, str], WarmPool] = {}
        # controller-side shared compile-cache tier (served to worker
        # hosts over the compile_cache_* verbs once attach_rpc runs)
        self.compile_tier = CompileCacheTier()
        self._rpc_server = None            # set by attach_rpc (multi-host)
        self._router_admins: list[str] = []
        # telemetry history + SLO engine (the proactive half of the
        # observability plane): the store aggregates this process's
        # registry deltas plus telem1 pushes from worker hosts; the
        # engine evaluates burn rates on the same tick. Page-severity
        # firings auto-capture an incident bundle (rate-limited).
        self.telemetry = TelemetryStore()
        self._telem_sampler = RegistrySampler()
        self.slo = SLOEngine(
            self.telemetry, on_page=self._slo_page_hook, logger=self.logger
        )
        self.telemetry_interval_s = float(
            os.environ.get("BIOENGINE_TELEM_PUSH_S", "10")
        )
        self._telemetry_task: Optional[asyncio.Task] = None
        self.slo_bundles: deque = deque(maxlen=4)   # auto-captured artifacts
        self._slo_bundle_last: dict[tuple[str, str], float] = {}
        # ---- durable control plane (serving/journal.py) -----------------
        # intent journal + snapshot under control_dir /
        # BIOENGINE_CONTROL_DIR; None = memory-only (exactly the old
        # behavior). Every start MINTS a persisted monotonic epoch —
        # the fence hosts use to reject verbs from a revived old
        # controller — whether or not recover() is ever called.
        self.journal = (
            ControlJournal(control_dir)
            if control_dir
            else ControlJournal.from_env()
        )
        self._journal_state = None
        self.phase = "ACTIVE"              # ACTIVE | RECOVERING
        self.reconcile_report: Optional[dict] = None
        self._recover_deadline: Optional[float] = None
        # mesh shards reported by rejoining hosts, keyed by the mesh
        # replica id they belong to — a MeshReplica is rebuilt once
        # every stage has reported (serving/journal.py module docstring)
        self._pending_mesh_shards: dict[str, dict[int, dict]] = {}
        # complete-but-surplus meshes (intent already satisfied when
        # the last stage reported): their earlier stages were answered
        # "kept" before the surplus was knowable, so the settle sweep
        # must stop them host-side
        self._surplus_mesh_shards: dict[str, dict[int, dict]] = {}
        self.reconcile_grace_s = float(
            os.environ.get("BIOENGINE_RECONCILE_GRACE_S", "20")
        )
        if self.journal is not None:
            self._journal_state = self.journal.load()
            self.journal.snapshot_provider = self._journal_snapshot_state
            self.epoch = self.journal.mint_epoch()
        else:
            self.epoch = 1
        CONTROLLER_EPOCH.set(self.epoch)
        flight.record(
            "controller.epoch",
            epoch=self.epoch,
            journaled=self.journal is not None,
        )
        _CONTROLLERS.add(self)             # scrape-time serving gauges

    # ---- multi-host control plane -------------------------------------------

    def attach_rpc(self, server, admin_users: Optional[list[str]] = None) -> None:
        """Enable multi-host placement: registers the ``serve-router``
        service that (a) worker hosts join through (``register_host``)
        and (b) remote deployments route composition calls back through
        (``route_call`` — the cross-host analog of a Serve
        DeploymentHandle call, ref apps/builder.py:1474-1508)."""
        from bioengine_tpu.utils.permissions import (
            check_method_permission,
            check_permissions,
            is_authorized,
        )

        self._rpc_server = server
        self._router_admins = list(admin_users or [])
        if not self._router_admins and self._journal_state is not None:
            # a restarted controller attached without explicit admins
            # restores the journaled bindings (worker restarts normally
            # pass their own list, which then re-journals below)
            self._router_admins = list(self._journal_state.admins)
        # the welcome handshake advertises the fencing epoch so a host
        # can spot a stale controller before exchanging any verbs
        server.epoch = self.epoch
        if self.journal is not None and self._router_admins:
            # via _journal_append: a full/readonly disk degrades
            # durability, never controller attach, and the folded
            # snapshot view keeps the RECOVERING flag accurate
            self._journal_append(
                "admins", {"admins": list(self._router_admins)}
            )

        async def route_call(
            app_id, deployment, method, args=None, kwargs=None, context=None
        ):
            # Same per-method ACL the front-door proxy enforces
            # (apps/proxy.py) — route_call must not be a side door.
            # Admins (incl. worker hosts holding the admin token, whose
            # composition handles route through here) always pass.
            if not is_authorized(context, self._router_admins):
                app = self.apps.get(app_id)
                acl = app.acl if app is not None else None
                check_method_permission(acl or [], method, context)
            handle = self.get_handle(app_id, deployment)
            return await handle.call(method, *(args or []), **(kwargs or {}))

        def register_host(
            host_id,
            service_id,
            topology,
            worker_tag=None,
            replicas=None,
            clock_skew_s=0.0,
            context=None,
        ):
            check_permissions(context, self._router_admins, "register_host")
            self.cluster_state.register_host(
                host_id, service_id, topology, worker_tag,
                clock_skew_s=clock_skew_s,
            )
            # reconcile a REJOINING host's still-warm replicas: each one
            # the controller still routes to this host is re-adopted
            # (service id + chip lease restored); anything already
            # re-placed elsewhere is returned for the host to discard
            drop_replicas = []
            for info in replicas or []:
                # two reconciliation paths: a warm replica the routing
                # set still knows (blip rejoin) is re-adopted in place;
                # during RECOVERY the routing set is empty, so a replica
                # matching journaled intent is adopted from the report
                # instead. Anything matching neither is dropped — the
                # journal is the intent of record.
                if self._readopt_replica(host_id, service_id, info):
                    continue
                if self._adopt_reported_replica(host_id, service_id, info):
                    continue
                if self.phase == "RECOVERING":
                    RECONCILE_DROPPED.inc()
                    if self.reconcile_report is not None:
                        self.reconcile_report["dropped"] += 1
                drop_replicas.append(info.get("replica_id"))
            self.logger.info(
                f"host '{host_id}' joined with "
                f"{topology.get('n_chips', 0)} chips ({service_id})"
                + (
                    f"; re-adopted {len(replicas or []) - len(drop_replicas)}"
                    f"/{len(replicas)} warm replicas"
                    if replicas
                    else ""
                )
            )
            if replicas:
                self._replicas_changed.set()
            flight.record(
                "host.join",
                host=host_id,
                service_id=service_id,
                chips=topology.get("n_chips", 0),
                warm_replicas=len(replicas or []),
                dropped=len(drop_replicas),
            )
            return {
                "host_id": host_id,
                "registered": True,
                "drop_replicas": drop_replicas,
                # the fencing epoch: the host records it and rejects
                # replica verbs stamped with anything lower
                "epoch": self.epoch,
            }

        def deregister_host(host_id, context=None):
            check_permissions(context, self._router_admins, "deregister_host")
            orphans = self.cluster_state.mark_host_dead(host_id)
            return {"host_id": host_id, "orphaned_replicas": orphans}

        def push_telemetry(host_id, snapshot, context=None):
            # capability telem1: worker hosts push periodic registry
            # deltas here. A push from THIS process (the in-process
            # multi-host harness shares one registry, which the local
            # sampler already covers) is dropped by source identity —
            # the same dedup rule flight.merge_records applies.
            check_permissions(context, self._router_admins, "push_telemetry")
            if (
                isinstance(snapshot, dict)
                and snapshot.get("source_id") == self._telem_sampler.source_id
            ):
                return {"host_id": host_id, "accepted": 0, "deduped": True}
            # de-skew: captured_at is the PUSHER's wall clock — shift it
            # onto the controller's timeline with the offset the host
            # measured at its handshake, or a fast host's future-dated
            # buckets would swallow every on-time sample behind them
            record = self.cluster_state.hosts.get(host_id)
            if (
                record is not None
                and record.clock_skew_s
                and isinstance(snapshot, dict)
                and snapshot.get("captured_at") is not None
            ):
                snapshot = {
                    **snapshot,
                    "captured_at": float(snapshot["captured_at"])
                    - record.clock_skew_s,
                }
            accepted = self.telemetry.ingest(snapshot, host_id=host_id)
            return {"host_id": host_id, "accepted": accepted}

        def compile_cache_list(context=None):
            check_permissions(context, self._router_admins, "compile_cache_list")
            return self.compile_tier.list()

        def compile_cache_fetch(name, context=None):
            # bulk bytes ride the zero-copy OOB transport frame on the
            # way back; None = tier miss (the host compiles as usual)
            check_permissions(
                context, self._router_admins, "compile_cache_fetch"
            )
            return self.compile_tier.fetch(name)

        def compile_cache_publish(name, blob, context=None):
            check_permissions(
                context, self._router_admins, "compile_cache_publish"
            )
            return {"name": name, "stored": self.compile_tier.publish(name, blob)}

        def get_routing_table(
            router_id=None, since_version=0, staleness_s=None, context=None
        ):
            # the scale-out router tier syncs its epoch-stamped table
            # here (serving/router.py StandaloneRouter.sync_once);
            # admin-gated like the other control verbs — a router holds
            # the same token a worker host does
            check_permissions(
                context, self._router_admins, "get_routing_table"
            )
            return self.router_publisher.table(
                since_version=int(since_version or 0),
                router_id=router_id,
                staleness_s=staleness_s,
            )

        server.register_local_service(
            {
                "id": "serve-router",
                "name": "Serving controller router",
                "type": "bioengine-serve-router",
                # public visibility: every method self-enforces
                # (register/deregister_host require admin; route_call
                # enforces the target app's per-method ACL above)
                "config": {"require_context": True, "visibility": "public"},
                "route_call": route_call,
                "register_host": register_host,
                "deregister_host": deregister_host,
                "push_telemetry": push_telemetry,
                "compile_cache_list": compile_cache_list,
                "compile_cache_fetch": compile_cache_fetch,
                "compile_cache_publish": compile_cache_publish,
                "get_routing_table": get_routing_table,
            }
        )

    async def _call_host(
        self,
        service_id: str,
        method: str,
        *args,
        rpc_timeout: Optional[float] = None,
        **kwargs,
    ):
        if self._rpc_server is None:
            raise RuntimeError("controller has no RPC server attached")
        if method in _EPOCH_STAMPED_VERBS and self._rpc_server.service_peer_supports(
            service_id, PROTO_EPOCH1
        ):
            # every placement/lifecycle verb carries this controller's
            # epoch; a host that has seen a newer one rejects it typed
            # (StaleEpochError) — the split-brain fence. A pre-epoch1
            # host never declared the capability, so it gets the legacy
            # signature (and no fence) instead of an unexpected-kwarg
            # TypeError on every placement
            kwargs.setdefault("epoch", self.epoch)
        return await self._rpc_server.call_service_method(
            service_id, method, args, kwargs,
            **({"timeout": rpc_timeout} if rpc_timeout else {}),
        )

    async def _stream_host(self, service_id: str, method: str, *args, **kwargs):
        """Streaming twin of :meth:`_call_host`: bridges a host's
        async-generator verb (``replica_stream``) through the RPC
        server's stream1 plane, yielding items as their frames land."""
        if self._rpc_server is None:
            raise RuntimeError("controller has no RPC server attached")
        async for item in self._rpc_server.call_service_stream(
            service_id, method, args, kwargs
        ):
            yield item

    # ---- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.create_task(self._health_loop())
        if self._telemetry_task is None:
            self._telemetry_task = asyncio.create_task(self._telemetry_loop())

    async def stop(self) -> None:
        if self._health_task:
            self._health_task.cancel()
            self._health_task = None
        if self._telemetry_task:
            self._telemetry_task.cancel()
            self._telemetry_task = None
        for app_id in list(self.apps):
            await self.undeploy(app_id)

    async def _telemetry_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.telemetry_interval_s)
                self.telemetry_tick()
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self.logger.error(f"telemetry tick error: {e}")

    def telemetry_tick(self) -> None:
        """One observation pass: fold this process's registry deltas
        into the telemetry store, then run the SLO/anomaly evaluation.
        The periodic loop calls this; tests and the CI dryrun drive it
        directly for determinism."""
        snapshot = self._telem_sampler.sample()
        if snapshot:
            self.telemetry.ingest(snapshot, host_id="controller")
        # SLO verdicts are deferred while RECOVERING: burn rates
        # computed over a half-seen cluster would fire (and feed scale
        # pressure) on recovery noise, not service behavior
        if self.slo.deployments() and self.phase != "RECOVERING":
            self.slo.evaluate()

    # ---- deploy / undeploy --------------------------------------------------

    async def deploy(
        self, app_id: str, specs: list[DeploymentSpec], acl: Any = None
    ) -> AppDeployment:
        existing = self.apps.get(app_id)
        if existing is not None:
            if existing.status in ("DEPLOY_FAILED", "STOPPED"):
                del self.apps[app_id]  # failed attempt may be retried
            else:
                raise ValueError(f"app '{app_id}' already deployed")
        app = AppDeployment(
            app_id=app_id, specs={s.name: s for s in specs}, acl=acl
        )
        self.apps[app_id] = app
        # intent commit: the deploy is ACCEPTED (validated specs, app
        # registered) — journal it now so a crash mid-placement recovers
        # to "place this app", never to silence. Placement failures roll
        # the record back below.
        self._journal_append(
            "deploy",
            {
                "app_id": app_id,
                "specs": [spec_to_dict(s) for s in specs],
                "acl": acl,
            },
        )
        try:
            for spec in specs:
                app.replicas[spec.name] = []
                self._init_deployment_plumbing(app_id, spec)
                for _ in range(spec.num_replicas):
                    await self._add_replica(app, spec)
            # pools fill AFTER every serving replica is placed — a tight
            # cluster spends its chips on the routing set first
            for spec in specs:
                if (app_id, spec.name) in self._warm_pools:
                    await self._top_up_warm_pool(app, spec)
            app.status = "RUNNING"
            self.logger.info(f"app '{app_id}' deployed")
        except Exception:
            # Roll back partial state: stop started replicas and release
            # their chip leases so a failed deploy leaks nothing.
            app.status = "DEPLOY_FAILED"
            # the intent did not commit — a recovering controller must
            # not resurrect a deploy that never finished
            self._journal_append("undeploy", {"app_id": app_id})
            self.slo.unregister(app_id)
            for spec in specs:
                sched = self._schedulers.pop((app_id, spec.name), None)
                if sched is not None:
                    await sched.close()
                pool = self._warm_pools.pop((app_id, spec.name), None)
                if pool is not None:
                    for r in pool.drain_all():
                        try:
                            await r.stop()
                        finally:
                            self.cluster_state.mark_replica_dead(r.replica_id)
            for replicas in app.replicas.values():
                for r in replicas:
                    try:
                        await r.stop()
                    finally:
                        self.cluster_state.mark_replica_dead(r.replica_id)
            raise
        return app

    def _init_deployment_plumbing(self, app_id: str, spec: DeploymentSpec) -> None:
        """Per-deployment controller plumbing shared by ``deploy`` and
        journal recovery: the opt-in global scheduler, SLO tracking,
        and the warm pool shell (pools FILL later — after serving
        replicas, or after reconcile settles)."""
        if spec.scheduling is not None and spec.scheduling.enabled:
            scheduler = DeploymentScheduler(
                self,
                app_id,
                spec.name,
                spec,
                spec.scheduling,
                scorer=self.scorer_factory(),
            )
            self._schedulers[(app_id, spec.name)] = scheduler
            if spec.scheduling.slo_pressure and spec.slo is not None:
                # close the loop: the predictive autoscaler may
                # consume budget burn as an up-pressure signal
                # (opt-in — scheduling.slo_pressure)
                scheduler.pressure_fn = (
                    lambda a=app_id, d=spec.name: self.slo.burn_pressure(a, d)
                )
        if spec.slo is not None:
            self.slo.register(app_id, spec.name, spec.slo)
        if spec.warm_pool is not None and spec.warm_pool.size > 0:
            self._warm_pools[(app_id, spec.name)] = WarmPool(
                app_id, spec.name, spec.warm_pool
            )

    # ---- durable control plane: journal + crash recovery --------------------

    def _journal_snapshot_state(self) -> tuple:
        """Lazy snapshot provider: the journal pulls the folded intent
        only when a compaction actually fires (1-in-snapshot_every
        appends, plus the explicit recover/settle snapshots) — a plain
        append never pays the full-fleet spec serialization."""
        apps = {
            app_id: {
                "specs": [spec_to_dict(s) for s in app.specs.values()],
                "acl": app.acl,
            }
            for app_id, app in self.apps.items()
            if app.status not in ("STOPPED", "DEPLOY_FAILED")
        }
        return apps, self._router_admins, self.phase == "RECOVERING"

    def _journal_append(self, op: str, data: dict) -> None:
        if self.journal is None:
            return
        try:
            self.journal.append(op, data)
        except OSError as e:
            # a full/readonly disk must degrade durability, not serving
            self.logger.error(f"journal append failed ({op}): {e}")

    async def recover(self) -> dict:
        """Rebuild declarative intent from snapshot + journal into a
        ``RECOVERING`` phase: apps exist with their full specs (so
        routing, handles and new deploys work) but with EMPTY replica
        sets. Live hosts rejoin with their warm-replica inventory and
        :meth:`_adopt_reported_replica` re-adopts matching replicas in
        place; after ``BIOENGINE_RECONCILE_GRACE_S`` (or once every
        intent is satisfied) the health loop settles the diff —
        re-placing only what no host still serves — and flips the
        phase to ``ACTIVE``. Until then autoscale and SLO verdicts are
        DEFERRED: a half-seen cluster must not be "scaled down"."""
        if self.journal is None:
            raise RuntimeError(
                "recovery needs a control journal "
                "(control_dir= or BIOENGINE_CONTROL_DIR)"
            )
        state = self._journal_state or self.journal.load()
        report = {
            "epoch": self.epoch,
            "apps": 0,
            "adopted": 0,
            "replaced": 0,
            "dropped": 0,
            "mesh_rebuilt": 0,
            "torn_tail": state.torn_tail,
            "started_at": time.time(),
            "settled_at": None,
        }
        self.reconcile_report = report
        self._recover_started_mono = time.monotonic()
        if state.admins and not self._router_admins:
            self._router_admins = list(state.admins)
        for app_id, entry in state.apps.items():
            if app_id in self.apps:
                continue  # double recover() is a no-op per app
            specs = [
                spec_from_dict(
                    sd,
                    app_id,
                    make_handle=lambda name, a=app_id: self.get_handle(
                        a, name
                    ),
                )
                for sd in entry.get("specs", [])
            ]
            app = AppDeployment(
                app_id=app_id,
                specs={s.name: s for s in specs},
                acl=entry.get("acl"),
            )
            app.status = "RECOVERING"
            self.apps[app_id] = app
            for spec in specs:
                app.replicas[spec.name] = []
                self._init_deployment_plumbing(app_id, spec)
            report["apps"] += 1
        if report["apps"]:
            self.phase = "RECOVERING"
            self._recover_deadline = (
                time.monotonic() + self.reconcile_grace_s
            )
            self._wake_health.set()
        flight.record(
            "controller.recovering",
            severity="warning",
            epoch=self.epoch,
            apps=report["apps"],
            records_replayed=state.records_replayed,
            torn_tail=state.torn_tail,
            snapshot=state.snapshot_loaded,
        )
        self.logger.info(
            f"recovered intent for {report['apps']} app(s) from "
            f"{self.journal.directory} (epoch {self.epoch}, "
            f"{state.records_replayed} journal records"
            + (", TORN TAIL discarded" if state.torn_tail else "")
            + "); reconciling against host inventory"
        )
        # compact NOW, flagged recovering=True via the snapshot
        # provider (phase is RECOVERING here): a double-crash recovers
        # from this snapshot (the "snapshot written by a recovering
        # controller" edge case the tests pin)
        try:
            self.journal.write_snapshot()
        except OSError as e:
            self.logger.error(f"recovery snapshot failed: {e}")
        return report

    def adopt_recovered_specs(
        self, app_id: str, specs: list, acl: Any = None
    ) -> bool:
        """Re-attach a freshly BUILT app to its journal-recovered
        intent instead of re-deploying it. The apps manager's own
        record recovery redeploys every recorded app at worker start;
        when the control journal already resurrected the controller
        half (status ``RECOVERING``), a second ``deploy`` would be
        rejected as a duplicate — instead the recovered specs take the
        build's LIVE instance factories (so local placements stop
        paying the payload rebuild) and reconcile proceeds untouched.
        Returns False when the app is not in journal recovery (caller
        should deploy normally)."""
        app = self.apps.get(app_id)
        if app is None or app.status != "RECOVERING":
            return False
        for spec in specs:
            current = app.specs.get(spec.name)
            if current is None:
                # deployment added since the journal record: place it
                # like a deploy would, but through the reconcile path
                app.specs[spec.name] = spec
                app.replicas.setdefault(spec.name, [])
                self._init_deployment_plumbing(app_id, spec)
            else:
                # keep the recovered spec OBJECT (schedulers and warm
                # pools hold references to it) — swap in the live build
                current.instance_factory = spec.instance_factory
                current.remote_payload = spec.remote_payload
        if acl is not None:
            app.acl = acl
        self.logger.info(
            f"app '{app_id}' re-attached to journal-recovered intent "
            f"({len(specs)} spec(s))"
        )
        return True

    def _adopt_reported_replica(
        self, host_id: str, service_id: str, info: dict
    ) -> bool:
        """RECOVERY adoption: a rejoining host reports a warm replica
        the (restarted) controller's routing set does not know. If
        journaled intent covers it — app recovered, deployment spec
        present, replica count under the intent — adopt it IN PLACE:
        same replica_id, chips re-leased via ``host_adopt_chips``, no
        restart. Mesh shards buffer until every stage reports, then a
        MeshReplica is rebuilt around them. Anything else returns
        False and the host is told to drop its copy."""
        app = self.apps.get(info.get("app_id", ""))
        if app is None or app.status != "RECOVERING":
            return False
        dep = info.get("deployment", "")
        spec = app.specs.get(dep)
        if spec is None:
            return False
        rid = info.get("replica_id") or ""
        if info.get("mesh_shard"):
            return self._adopt_mesh_shard(
                app, spec, host_id, service_id, info
            )
        existing = app.replicas.setdefault(dep, [])
        for r in existing:
            if r.replica_id != rid:
                continue
            # idempotent re-report (host re-registered twice). This
            # branch is only reached when _readopt_replica declined —
            # wrong host, non-routable state, or a lease conflict —
            # so "keep" must re-establish the lease on the freshly
            # reset HostRecord, not just wave the copy through.
            if getattr(r, "host_id", None) != host_id:
                return False  # duplicate id reported by the wrong host
            try:
                self.cluster_state.host_adopt_chips(
                    host_id, rid, list(info.get("device_ids") or [])
                )
            except Exception as e:  # noqa: BLE001 — lease conflict = drop
                self.logger.warning(
                    f"cannot re-lease re-reported {rid} on "
                    f"'{host_id}': {e}"
                )
                return False
            return True
        if len(existing) >= spec.num_replicas:
            return False  # intent already satisfied — surplus copy
        try:
            reported = ReplicaState(info.get("state", ""))
        except ValueError:
            return False
        if reported not in ROUTABLE_STATES + (ReplicaState.INITIALIZING,):
            return False
        device_ids = list(info.get("device_ids") or [])
        try:
            self.cluster_state.host_adopt_chips(host_id, rid, device_ids)
        except Exception as e:  # noqa: BLE001 — lease conflict = don't adopt
            self.logger.warning(
                f"recovery cannot adopt {rid} on '{host_id}': {e}"
            )
            return False
        replica = RemoteReplica(
            app_id=app.app_id,
            deployment_name=dep,
            host_id=host_id,
            host_service_id=service_id,
            call_host=self._call_host,
            stream_host=self._stream_host,
            payload=spec.remote_payload or {},
            device_ids=device_ids,
            max_ongoing_requests=spec.max_ongoing_requests,
            log_sink=self.cluster_state.append_replica_log,
        )
        replica.replica_id = rid  # the host's copy IS the identity
        replica.state = reported
        self.cluster_state.register_replica(
            app.app_id, dep, rid, device_ids, host_id=host_id
        )
        existing.append(replica)
        self._replicas_changed.set()
        RECONCILE_ADOPTED.inc()
        if self.reconcile_report is not None:
            self.reconcile_report["adopted"] += 1
        self.logger.info(
            f"recovery adopted {rid} on '{host_id}' "
            f"({app.app_id}/{dep}, state={reported.value})"
        )
        flight.record(
            "replica.readopt",
            replica=rid,
            app=app.app_id,
            host=host_id,
            state=reported.value,
            recovery=True,
        )
        return True

    def _adopt_mesh_shard(
        self, app: AppDeployment, spec: DeploymentSpec,
        host_id: str, service_id: str, info: dict,
    ) -> bool:
        """Buffer one reported mesh shard; once all ``spec.mesh.stages``
        stages have reported, rebuild the MeshReplica around them (same
        mesh replica id, shard chips re-leased under it, no shard
        restarts). Incomplete meshes left at settle are swept — the
        orphan shards stopped and the mesh re-placed from spec."""
        if spec.mesh is None:
            return False
        from bioengine_tpu.serving.mesh_plan import MeshPlan, ShardAssignment

        shard_info = info.get("mesh_shard") or {}
        rid = info.get("replica_id") or ""
        mesh_rid = shard_info.get("mesh_replica_id") or (
            rid.rsplit("-s", 1)[0] if "-s" in rid else ""
        )
        try:
            stage = int(shard_info.get("stage", -1))
        except (TypeError, ValueError):
            return False
        if not mesh_rid or stage < 0 or stage >= spec.mesh.stages:
            return False
        dep = spec.name
        existing = app.replicas.setdefault(dep, [])
        if any(r.replica_id == mesh_rid for r in existing):
            # mesh already rebuilt; this shard belongs to it — but the
            # re-register reset this host's lease table, so the chips
            # must be re-leased under the mesh id or the ledger shows
            # them free and a later placement double-leases the devices
            try:
                self.cluster_state.host_adopt_chips(
                    host_id, mesh_rid, list(info.get("device_ids") or [])
                )
            except Exception as e:  # noqa: BLE001 — lease conflict = drop
                self.logger.warning(
                    f"cannot re-lease shard {rid} of rebuilt mesh "
                    f"{mesh_rid} on '{host_id}': {e}"
                )
                return False
            return True
        pending = self._pending_mesh_shards.setdefault(mesh_rid, {})
        pending[stage] = {
            "host_id": host_id,
            "service_id": service_id,
            "device_ids": list(info.get("device_ids") or []),
            "state": info.get("state"),
            "app_id": app.app_id,
            "deployment": dep,
        }
        if len(pending) < spec.mesh.stages:
            return True  # keep the shard; siblings may still report
        if len(existing) >= spec.num_replicas:
            # surplus mesh: THIS reporter is told to drop its shard,
            # but the sibling stages were already answered "kept" —
            # park them for the settle sweep to stop host-side, else
            # they'd serve unrouted and hold chip leases forever
            self._pending_mesh_shards.pop(mesh_rid, None)
            pending.pop(stage, None)
            if pending:
                # handoff, not a leak: _reconcile_settle stops these
                # shards host-side and clear()s the whole map when the
                # recovery grace window closes
                # bioengine: ignore[BE-LIFE-401]
                self._surplus_mesh_shards[mesh_rid] = pending
            return False
        shards = [
            ShardAssignment(
                stage=s,
                host_id=sh["host_id"],
                service_id=sh["service_id"],
                n_chips=len(sh["device_ids"]),
                device_ids=list(sh["device_ids"]),
            )
            for s, sh in sorted(pending.items())
        ]
        try:
            for sh in shards:
                self.cluster_state.host_adopt_chips(
                    sh.host_id, mesh_rid, sh.device_ids
                )
        except Exception as e:  # noqa: BLE001 — lease conflict = don't adopt
            self.logger.warning(
                f"recovery cannot adopt mesh {mesh_rid}: {e}"
            )
            self.cluster_state.release_chips(mesh_rid)
            return False
        replica = MeshReplica(
            app_id=app.app_id,
            deployment_name=dep,
            plan=MeshPlan(config=spec.mesh, shards=shards),
            call_host=self._call_host,
            payload=spec.remote_payload or {},
            max_ongoing_requests=spec.max_ongoing_requests,
            log_sink=self.cluster_state.append_replica_log,
            stream_host=self._stream_host,
        )
        replica.replica_id = mesh_rid
        replica.state = ReplicaState.HEALTHY
        self.cluster_state.register_replica(
            app.app_id, dep, mesh_rid, replica.device_ids,
            host_id=replica.host_id,
        )
        existing.append(replica)
        self._pending_mesh_shards.pop(mesh_rid, None)
        self._replicas_changed.set()
        RECONCILE_ADOPTED.inc()
        if self.reconcile_report is not None:
            self.reconcile_report["adopted"] += 1
            self.reconcile_report["mesh_rebuilt"] += 1
        self.logger.info(
            f"recovery rebuilt mesh {mesh_rid} over "
            f"{[s.host_id for s in shards]} ({app.app_id}/{dep})"
        )
        flight.record(
            "replica.readopt",
            replica=mesh_rid,
            app=app.app_id,
            host=replica.host_id,
            state=replica.state.value,
            recovery=True,
            mesh=True,
        )
        return True

    def _reconcile_satisfied(self) -> bool:
        for app in self.apps.values():
            if app.status != "RECOVERING":
                continue
            for name, spec in app.specs.items():
                if len(app.replicas.get(name, [])) < spec.num_replicas:
                    return False
        return not self._pending_mesh_shards

    async def _reconcile_tick(self) -> None:
        """RECOVERING-phase health tick: wait for hosts to rejoin and
        report; settle once every intent is satisfied or the grace
        window closes."""
        if not self._reconcile_satisfied():
            if (
                self._recover_deadline is None
                or time.monotonic() < self._recover_deadline
            ):
                return
        await self._reconcile_settle()

    async def _reconcile_settle(self) -> None:
        report = self.reconcile_report or {}
        # sweep incomplete mesh rebuilds (a sibling stage's host never
        # came back) AND complete-but-surplus meshes (intent already
        # satisfied; their early stages were answered "kept" before the
        # surplus was knowable): stop the shards host-side and let the
        # normal placement path re-place whatever the diff still needs
        orphan_meshes = {
            **self._pending_mesh_shards,
            **self._surplus_mesh_shards,
        }
        for mesh_rid, pending in orphan_meshes.items():
            for stage, sh in pending.items():
                try:
                    await self._call_host(
                        sh["service_id"], "stop_replica",
                        f"{mesh_rid}-s{stage}",
                    )
                except Exception as e:  # noqa: BLE001 — host may be gone
                    self.logger.debug(
                        f"orphan shard stop failed (tolerated): {e}"
                    )
            report["dropped"] = report.get("dropped", 0) + 1
            RECONCILE_DROPPED.inc()
        self._pending_mesh_shards.clear()
        self._surplus_mesh_shards.clear()
        # re-place only the DIFF: what no surviving host still serves
        for app in list(self.apps.values()):
            if app.status != "RECOVERING":
                continue
            for name, spec in app.specs.items():
                while (
                    len(app.replicas.get(name, [])) < spec.num_replicas
                ):
                    try:
                        await self._add_replica(app, spec)
                    except Exception as e:  # noqa: BLE001 — capacity may come later
                        self.logger.warning(
                            f"recovery re-place blocked for "
                            f"{app.app_id}/{name}: {e}"
                        )
                        break
                    report["replaced"] = report.get("replaced", 0) + 1
                    RECONCILE_REPLACED.inc()
            app.status = "RUNNING"
            for name, spec in app.specs.items():
                if (app.app_id, name) in self._warm_pools:
                    spawn_supervised(
                        self._top_up_warm_pool(app, spec),
                        name=f"recover-warmpool-{app.app_id}-{name}",
                        logger=self.logger,
                    )
        self.phase = "ACTIVE"
        self._recover_deadline = None
        report["settled_at"] = time.time()
        self._replicas_changed.set()
        # the settled state is the new baseline snapshot (the provider
        # reports recovering=False now that the phase is ACTIVE)
        if self.journal is not None:
            try:
                self.journal.write_snapshot()
            except OSError as e:
                self.logger.error(f"settle snapshot failed: {e}")
        flight.record(
            "controller.recovered",
            epoch=self.epoch,
            adopted=report.get("adopted", 0),
            replaced=report.get("replaced", 0),
            dropped=report.get("dropped", 0),
            mesh_rebuilt=report.get("mesh_rebuilt", 0),
            duration_s=round(
                time.monotonic()
                - getattr(self, "_recover_started_mono", time.monotonic()),
                3,
            ),
        )
        self.logger.info(
            f"reconcile settled: adopted={report.get('adopted', 0)} "
            f"replaced={report.get('replaced', 0)} "
            f"dropped={report.get('dropped', 0)} "
            f"mesh_rebuilt={report.get('mesh_rebuilt', 0)} "
            f"(epoch {self.epoch})"
        )

    async def _add_replica(
        self,
        app: AppDeployment,
        spec: DeploymentSpec,
        avoid_hosts: Any = (),
    ):
        """Place one replica: locally when this host has the chips, else
        on a joined worker host with capacity (RPC-backed RemoteReplica),
        else enqueue a pending workload for the provisioner.
        ``avoid_hosts`` steers a mesh re-plan around hosts the replaced
        replica degraded on (dead hosts are excluded anyway; this
        covers alive-but-faulty ones)."""
        from bioengine_tpu.utils.tracing import span

        with span(
            "add_replica", app_id=app.app_id, deployment=spec.name,
            chips=spec.chips_per_replica,
        ):
            return await self._add_replica_inner(
                app, spec, avoid_hosts=avoid_hosts
            )

    async def _add_replica_inner(
        self,
        app: AppDeployment,
        spec: DeploymentSpec,
        avoid_hosts: Any = (),
    ):
        # warm-pool fast path: a scale-up or preemption restart PROMOTES
        # a pre-started standby (instance built, weights resident,
        # programs warm) instead of paying the cold start — the pool
        # refills itself in the background
        pool = self._warm_pools.get((app.app_id, spec.name))
        if pool is not None:
            dead_hosts = {
                h.host_id
                for h in self.cluster_state.hosts.values()
                if not h.alive
            }
            promoted = pool.pop_routable(skip_hosts=dead_hosts)
            if promoted is not None:
                app.replicas[spec.name].append(promoted)
                self.cluster_state.remove_pending(f"{app.app_id}/{spec.name}")
                self._replicas_changed.set()
                self.logger.info(
                    f"promoted warm standby {promoted.replica_id} for "
                    f"{app.app_id}/{spec.name} "
                    f"(pool occupancy {len(pool.standbys)})"
                )
                flight.record(
                    "replica.place",
                    replica=promoted.replica_id,
                    app=app.app_id,
                    deployment=spec.name,
                    host=getattr(promoted, "host_id", None),
                    device_ids=list(promoted.device_ids),
                    warm_pool=True,
                )
                if pool.config.refill:
                    spawn_supervised(
                        self._top_up_warm_pool(app, spec),
                        name=f"warmpool-refill-{app.app_id}-{spec.name}",
                        logger=self.logger,
                    )
                return promoted
        replica = await self._place_new_replica(
            app, spec, avoid_hosts=avoid_hosts
        )
        app.replicas[spec.name].append(replica)
        self.cluster_state.remove_pending(f"{app.app_id}/{spec.name}")
        self._replicas_changed.set()  # wake requests parked in _pick_replica_wait
        flight.record(
            "replica.place",
            replica=replica.replica_id,
            app=app.app_id,
            deployment=spec.name,
            host=getattr(replica, "host_id", None),
            device_ids=list(replica.device_ids),
        )
        return replica

    async def _place_new_replica(
        self,
        app: AppDeployment,
        spec: DeploymentSpec,
        pending_on_fail: bool = True,
        record_failed: bool = True,
        avoid_hosts: Any = (),
    ):
        """Place and START one replica (local chips → joined host →
        pending workload) WITHOUT adding it to the routing set — shared
        by the serving path (_add_replica) and the warm-pool fill.
        ``record_failed`` keeps the legacy behavior of surfacing a
        start-failed replica in app.replicas (the health loop retires
        it); pool fills opt out — a failed standby just isn't a standby."""
        if spec.mesh is not None:
            return await self._place_mesh_replica(
                app, spec, pending_on_fail=pending_on_fail,
                record_failed=record_failed, avoid_hosts=avoid_hosts,
            )
        replica = None
        host_id = None
        if spec.chips_per_replica > 0 and (
            self.cluster_state.free_chips() < spec.chips_per_replica
        ):
            replica = self._make_remote_replica(app, spec)
            if replica is None:
                # No capacity anywhere: surface as pending workload so
                # the provisioner can scale out (ref manager.py:239-353's
                # SLURM headroom allowance).
                if pending_on_fail:
                    self.cluster_state.add_pending(
                        f"{app.app_id}/{spec.name}",
                        {"chips": spec.chips_per_replica},
                    )
                raise RuntimeError(
                    f"need {spec.chips_per_replica} chips for "
                    f"{app.app_id}/{spec.name}: none free locally or on "
                    f"any joined host"
                )
            host_id = replica.host_id
        if replica is None:
            replica = Replica(
                app_id=app.app_id,
                deployment_name=spec.name,
                instance_factory=spec.instance_factory,
                max_ongoing_requests=spec.max_ongoing_requests,
                log_sink=self.cluster_state.append_replica_log,
                batch_config=spec.batch_config(),
            )
            if spec.chips_per_replica > 0:
                replica.device_ids = self.cluster_state.acquire_chips(
                    replica.replica_id, spec.chips_per_replica
                )
        self.cluster_state.register_replica(
            app.app_id,
            spec.name,
            replica.replica_id,
            replica.device_ids,
            host_id=host_id,
        )
        try:
            await replica.start()
        except Exception:
            self.cluster_state.mark_replica_dead(replica.replica_id)
            if record_failed:
                app.replicas[spec.name].append(replica)
            raise
        return replica

    # ---- multi-host mesh placement ------------------------------------------

    def _mesh_capable_hosts(self) -> list:
        """Alive hosts whose connection declared the ``mesh1``
        capability at its handshake — a legacy host that cannot honor a
        ``mesh_shard`` start is never planned onto."""
        if self._rpc_server is None:
            return []
        return [
            h
            for h in self.cluster_state.hosts.values()
            if h.alive
            and self._rpc_server.service_peer_supports(
                h.service_id, PROTO_MESH1
            )
        ]

    async def _place_mesh_replica(
        self,
        app: AppDeployment,
        spec: DeploymentSpec,
        pending_on_fail: bool = True,
        record_failed: bool = True,
        avoid_hosts: Any = (),
    ):
        """Place one LOGICAL replica across several hosts' leases:
        plan (policy — serving/mesh_plan.py, scored through the same
        ``scorer_factory`` contract as scheduler placement), lease
        every shard's chips under the mesh replica's own id (so
        ``mark_replica_dead`` releases the whole mesh), then start the
        shards (execution — serving/mesh_replica.py). A restart after a
        host death lands here again and re-plans over the survivors —
        collapsing to a single-host fallback mesh when the config
        allows it."""
        if spec.remote_payload is None:
            raise MeshPlanError(
                f"{app.app_id}/{spec.name}: mesh placement needs a "
                f"remote payload (shards are built on worker hosts)"
            )
        try:
            plan = plan_mesh(
                spec.mesh,
                self._mesh_capable_hosts(),
                self.scorer_factory(),
                avoid_hosts=avoid_hosts,
            )
        except MeshPlanError as e:
            if pending_on_fail:
                # the provisioner's scale-up signal carries the chip
                # bill the PLANNER computed (the whole mesh, not one
                # host's slice — and a future partial-plan raise can
                # bill only the remainder)
                self.cluster_state.add_pending(
                    f"{app.app_id}/{spec.name}",
                    {"chips": e.chips_needed or spec.mesh.total_chips},
                )
            raise
        replica = MeshReplica(
            app_id=app.app_id,
            deployment_name=spec.name,
            plan=plan,
            call_host=self._call_host,
            payload=spec.remote_payload,
            max_ongoing_requests=spec.max_ongoing_requests,
            log_sink=self.cluster_state.append_replica_log,
            stream_host=self._stream_host,
        )
        for shard in plan.shards:
            shard.device_ids = self.cluster_state.host_acquire_chips(
                shard.host_id, replica.replica_id, shard.n_chips
            )
        replica.device_ids = [
            d for s in plan.shards for d in s.device_ids
        ]
        self.cluster_state.register_replica(
            app.app_id,
            spec.name,
            replica.replica_id,
            replica.device_ids,
            host_id=replica.host_id,
        )
        self.logger.info(
            f"placing {app.app_id}/{spec.name} as a "
            f"{spec.mesh.kind} x{spec.mesh.stages} mesh over "
            f"{plan.hosts} (chips {replica.device_ids})"
        )
        try:
            await replica.start()
        except Exception:
            # every shard lease rides the mesh replica id — one release
            self.cluster_state.mark_replica_dead(replica.replica_id)
            if record_failed:
                app.replicas[spec.name].append(replica)
            raise
        return replica

    # ---- warm pool ----------------------------------------------------------

    async def _top_up_warm_pool(
        self, app: AppDeployment, spec: DeploymentSpec
    ) -> None:
        """Fill the deployment's pool to its target size (config, or
        telemetry-grown toward max_size). Capacity shortfalls log and
        stop — a pool never queues pending workloads against the
        provisioner; serving replicas take that priority."""
        pool = self._warm_pools.get((app.app_id, spec.name))
        if pool is None:
            return
        target = pool.target_size(self.telemetry)
        # filling counts in-flight placements: a promotion-triggered
        # refill and a concurrent health tick must not both fill the
        # same slot (a cold start takes seconds — plenty of overlap)
        while len(pool.standbys) + pool.filling < target:
            if app.app_id not in self.apps or app.status == "STOPPED":
                return
            pool.filling += 1
            try:
                replica = await self._place_new_replica(
                    app, spec, pending_on_fail=False, record_failed=False
                )
            except Exception as e:  # noqa: BLE001 — capacity may come later
                pool.fill_failures += 1
                self.logger.warning(
                    f"warm-pool fill blocked for "
                    f"{app.app_id}/{spec.name}: {e}"
                )
                return
            finally:
                pool.filling -= 1
            if self._warm_pools.get((app.app_id, spec.name)) is not pool:
                # undeployed while the standby was starting
                await self._retire_replica(replica)
                return
            pool.add(replica)

    async def _warm_pool_tick(
        self, app: AppDeployment, spec: DeploymentSpec
    ) -> None:
        """Health-loop pool maintenance: standbys are health-checked
        (a preempted host's standby must not be promoted into a black
        hole), dead ones released, and the pool refilled to target."""
        pool = self._warm_pools.get((app.app_id, spec.name))
        if pool is None:
            return
        # bounded-concurrent checks, exactly like the serving replicas'
        # path — a dead host's standbys each cost a 30 s health timeout
        # and must not serialize the whole health loop behind it
        sem = asyncio.Semaphore(self.health_check_concurrency)

        async def checked(r) -> None:
            async with sem:
                try:
                    await r.check_health()
                except Exception:  # noqa: BLE001 — a throwing check is unhealthy
                    r.state = ReplicaState.UNHEALTHY

        await asyncio.gather(*(checked(r) for r in list(pool.standbys)))
        for dead in pool.remove_dead():
            self.logger.warning(
                f"warm standby {dead.replica_id} unhealthy; releasing"
            )
            try:
                await dead.stop()
            finally:
                self.cluster_state.mark_replica_dead(dead.replica_id)
        target = pool.target_size(self.telemetry)
        # shrink an over-target pool (telemetry sizing receded, or a
        # refill raced a promotion before the filling counter existed):
        # retire the youngest standby — idle chips go back to the fleet
        while len(pool.standbys) > target:
            victim = pool.standbys.pop()
            self.logger.info(
                f"warm pool over target for {app.app_id}/{spec.name}; "
                f"retiring standby {victim.replica_id}"
            )
            try:
                await victim.stop()
            finally:
                self.cluster_state.mark_replica_dead(victim.replica_id)
        if pool.config.refill and (
            len(pool.standbys) + pool.filling < target
        ):
            # a refill is a full cold start — run it off the health
            # loop (the filling counter keeps concurrent runs from
            # overfilling the same slot)
            spawn_supervised(
                self._top_up_warm_pool(app, spec),
                name=f"warmpool-tick-refill-{app.app_id}-{spec.name}",
                logger=self.logger,
            )

    def _readopt_replica(
        self, host_id: str, service_id: str, info: dict
    ) -> bool:
        """Reconcile one still-warm replica reported by a REJOINING
        host: if the controller still routes that replica_id to this
        host, restore its service binding + chip lease and (when the
        host reports it routable) clear the UNHEALTHY verdict the
        disconnect earned it. Returns False when the replica was
        already re-placed elsewhere — the host must discard its copy."""
        app = self.apps.get(info.get("app_id", ""))
        if app is None or app.status == "STOPPED":
            return False
        for r in app.replicas.get(info.get("deployment", ""), []):
            if r.replica_id != info.get("replica_id"):
                continue
            if not getattr(r, "is_remote", False) or r.host_id != host_id:
                return False
            if getattr(r, "is_mesh", False):
                # a mesh's identity spans hosts (and its inventory rows
                # carry shard ids, not the mesh id — this branch is a
                # belt under that suspender): one rejoining host can
                # never re-adopt it; the re-plan owns recovery
                return False
            try:
                reported = ReplicaState(info.get("state", ""))
            except ValueError:
                reported = ReplicaState.UNHEALTHY
            if reported not in ROUTABLE_STATES + (ReplicaState.INITIALIZING,):
                return False
            try:
                self.cluster_state.host_adopt_chips(
                    host_id, r.replica_id, list(r.device_ids)
                )
            except Exception as e:  # noqa: BLE001 — lease conflict = don't adopt
                self.logger.warning(
                    f"cannot re-adopt {r.replica_id} on '{host_id}': {e}"
                )
                return False
            r.host_service_id = service_id
            r.state = reported
            r.last_error = None
            self._breaker_counts.pop(r.replica_id, None)
            self.logger.info(
                f"re-adopted warm replica {r.replica_id} on rejoined "
                f"host '{host_id}' (state={reported})"
            )
            flight.record(
                "replica.readopt",
                replica=r.replica_id,
                app=app.app_id,
                host=host_id,
                state=reported.value,
            )
            return True
        return False

    def _make_remote_replica(
        self, app: AppDeployment, spec: DeploymentSpec
    ) -> Optional["RemoteReplica"]:
        if self._rpc_server is None or spec.remote_payload is None:
            return None
        self._prune_dead_hosts()  # never place on a host whose ws is gone
        host = self.cluster_state.find_host_for_chips(spec.chips_per_replica)
        if host is None:
            return None
        replica = RemoteReplica(
            app_id=app.app_id,
            deployment_name=spec.name,
            host_id=host.host_id,
            host_service_id=host.service_id,
            call_host=self._call_host,
            stream_host=self._stream_host,
            payload=spec.remote_payload,
            max_ongoing_requests=spec.max_ongoing_requests,
            log_sink=self.cluster_state.append_replica_log,
        )
        replica.device_ids = self.cluster_state.host_acquire_chips(
            host.host_id, replica.replica_id, spec.chips_per_replica
        )
        self.logger.info(
            f"placing {app.app_id}/{spec.name} on host '{host.host_id}' "
            f"(chips {replica.device_ids})"
        )
        return replica

    async def undeploy(
        self, app_id: str, drain_timeout_s: Optional[float] = None
    ) -> None:
        app = self.apps.pop(app_id, None)
        if app is None:
            return
        # intent commit: the undeploy is accepted the moment the app
        # leaves the routing map — a crash mid-teardown must not
        # resurrect the app at recovery
        self._journal_append("undeploy", {"app_id": app_id})
        # schedulers close FIRST: queued requests fail fast (typed) and
        # already-dispatched groups drain against replicas that are
        # still routable for a moment longer
        for name in app.specs:
            sched = self._schedulers.pop((app_id, name), None)
            if sched is not None:
                await sched.close()
        # warm standbys carry no traffic — retired alongside the
        # serving replicas so their chip leases release with the app
        standbys: list = []
        for name in app.specs:
            pool = self._warm_pools.pop((app_id, name), None)
            if pool is not None:
                standbys.extend(pool.drain_all())
        # drain-then-stop every replica concurrently: new calls are
        # rejected the moment states flip to DRAINING, in-flight
        # requests get up to drain_timeout_s to finish
        await asyncio.gather(
            *(
                self._retire_replica(r, drain_timeout_s)
                for replicas in app.replicas.values()
                for r in replicas
            ),
            *(self._retire_replica(r, drain_timeout_s) for r in standbys),
        )
        # router-state leak fix: get_handle/_pick_replica seeded
        # per-deployment entries that previously outlived the app —
        # unbounded growth under deploy/undeploy churn
        for name in app.specs:
            self._queue_depth.pop((app_id, name), None)
            self._rr_counters.pop((app_id, name), None)
            self._outliers.pop((app_id, name), None)
            # the SLO-page rate limiter seeds per-deployment stamps; a
            # redeploy under the same name must page immediately, not
            # inherit the dead app's cooldown (BE-LIFE-401)
            self._slo_bundle_last.pop((app_id, name), None)
        # observability-state sweep: a dead deployment must not keep
        # alerting or report history as live (get_telemetry races with
        # undeploy by design — see tests/test_slo.py churn test)
        self.slo.unregister(app_id)
        self.telemetry.sweep(app_id)
        app.status = "STOPPED"
        self.logger.info(f"app '{app_id}' undeployed")

    async def _retire_replica(
        self, replica, drain_timeout_s: Optional[float] = None
    ) -> None:
        try:
            await replica.stop(drain_timeout_s)
        finally:
            self.cluster_state.mark_replica_dead(replica.replica_id)
            self._breaker_counts.pop(replica.replica_id, None)
            self._forget_replica_latency(replica.replica_id)

    # ---- health + autoscaling loop ------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            try:
                try:
                    # a breaker trip wakes the loop immediately
                    await asyncio.wait_for(
                        self._wake_health.wait(), self.health_check_period
                    )
                except asyncio.TimeoutError:
                    pass
                self._wake_health.clear()
                await self.health_tick()
            except asyncio.CancelledError:
                return
            except Exception as e:
                self.logger.error(f"health loop error: {e}")

    async def health_tick(self) -> None:
        """One pass: health-check replicas, restart dead ones, autoscale.
        Apps are checked concurrently, and replicas within an app are
        checked concurrently under a per-app bound — one host hitting
        the 30 s ``replica_health`` timeout must not stall every other
        app's restart."""
        self._prune_dead_hosts()
        if self.phase == "RECOVERING":
            # reconcile owns this window: hosts are still rejoining and
            # reporting inventory, so restarts/autoscale/top-ups here
            # would double-place replicas a host is about to re-offer —
            # the verdicts are DEFERRED until the diff is settled
            await self._reconcile_tick()
            return
        # DEPLOYING apps are excluded: deploy() is still placing their
        # replicas, and a concurrent restart/top-up here would race it
        # into double-placed replicas and double-leased chips
        apps = [
            a
            for a in self.apps.values()
            if a.status not in ("DEPLOYING", "DEPLOY_FAILED", "STOPPED")
        ]
        await asyncio.gather(*(self._health_tick_app(a) for a in apps))

    async def _health_tick_app(self, app: AppDeployment) -> None:
        any_unhealthy = False
        sem = asyncio.Semaphore(self.health_check_concurrency)

        async def checked(r):
            async with sem:
                try:
                    return await r.check_health()
                except Exception as e:  # noqa: BLE001 — a throwing check is unhealthy
                    self.logger.error(
                        f"check_health raised for {r.replica_id}: {e}"
                    )
                    return ReplicaState.UNHEALTHY

        for spec_name, spec in app.specs.items():
            replicas = app.replicas.get(spec_name, [])
            snapshot = list(replicas)
            states = await asyncio.gather(*(checked(r) for r in snapshot))
            for r, state in zip(snapshot, states):
                if state != ReplicaState.UNHEALTHY:
                    continue
                any_unhealthy = True
                self.logger.warning(
                    f"restarting unhealthy replica {r.replica_id}"
                )
                await r.stop()
                self.cluster_state.mark_replica_dead(r.replica_id)
                self._breaker_counts.pop(r.replica_id, None)
                self._forget_replica_latency(r.replica_id)
                if r in replicas:
                    replicas.remove(r)
                try:
                    # a mesh replica remembers WHICH hosts its shards
                    # failed on — steer the re-plan around them (a dead
                    # host is excluded anyway; this covers the
                    # alive-but-faulty one, scored last-resort)
                    await self._add_replica(
                        app,
                        spec,
                        avoid_hosts=frozenset(
                            getattr(r, "degraded_hosts", ()) or ()
                        ),
                    )
                    self._replicas_changed.set()
                except Exception as e:
                    self.logger.error(
                        f"replica restart failed for "
                        f"{app.app_id}/{spec_name}: {e}"
                    )
            # top up a deployment that fell below its floor (e.g. a
            # restart failed for lack of capacity on an earlier tick, a
            # rejoining host was told to drop an already-re-placed
            # replica, or a recovery re-place was blocked at settle) —
            # without this the app would stay degraded even after
            # capacity returns. With autoscale the floor is
            # min_replicas (num_replicas tracks actual); with a PINNED
            # replica count, num_replicas IS the declared intent and
            # must be restored in full.
            floor = (
                spec.min_replicas
                if spec.autoscale
                else max(spec.min_replicas, spec.num_replicas)
            )
            while (
                len(
                    [
                        r
                        for r in app.replicas.get(spec_name, [])
                        if r.state
                        in ROUTABLE_STATES + (ReplicaState.INITIALIZING,)
                    ]
                )
                < floor
            ):
                try:
                    await self._add_replica(app, spec)
                    self._replicas_changed.set()
                except Exception as e:
                    self.logger.warning(
                        f"top-up blocked for {app.app_id}/{spec_name}: {e}"
                    )
                    break
            await self._autoscale(app, spec)
            await self._warm_pool_tick(app, spec)
            alive = [
                r
                for r in app.replicas.get(spec_name, [])
                if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING,
                               ReplicaState.INITIALIZING)
            ]
            if not alive:
                any_unhealthy = True
        app.status = "UNHEALTHY" if any_unhealthy else "RUNNING"

    def _prune_dead_hosts(self) -> None:
        """A host whose RPC service vanished (websocket closed) is dead:
        release its chip accounting so restarts can re-place its
        replicas. The replicas themselves go UNHEALTHY on their next
        check (transport error) and ride the normal restart path."""
        if self._rpc_server is None:
            return
        live_services = {
            s["id"] for s in self._rpc_server.list_services()
        }
        for host in list(self.cluster_state.hosts.values()):
            if host.alive and host.service_id not in live_services:
                orphans = self.cluster_state.mark_host_dead(host.host_id)
                self.logger.warning(
                    f"host '{host.host_id}' lost "
                    f"(orphaned replicas: {orphans})"
                )
                flight.record(
                    "host.dead",
                    severity="error",
                    host=host.host_id,
                    orphaned_replicas=list(orphans),
                )

    async def _autoscale(self, app: AppDeployment, spec: DeploymentSpec) -> None:
        if not spec.autoscale:
            return
        replicas = app.replicas.get(spec.name, [])
        # TESTING replicas carry real traffic (they are routable), so
        # they must count toward the load/scaling signal
        healthy = [
            r
            for r in replicas
            if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        if not healthy:
            return
        scheduler = self._schedulers.get((app.app_id, spec.name))
        if scheduler is not None:
            await self._autoscale_predictive(
                app, spec, scheduler, healthy, replicas
            )
            return
        avg_load = sum(r.load for r in healthy) / len(healthy)
        depth = self._queue_depth.get((app.app_id, spec.name), 0)
        if (
            avg_load > spec.target_load or depth > len(healthy) * spec.max_ongoing_requests
        ) and len(replicas) < spec.max_replicas:
            self.logger.info(
                f"autoscale UP {app.app_id}/{spec.name} "
                f"(load={avg_load:.2f}, depth={depth})"
            )
            try:
                await self._add_replica(app, spec)
                self._journal_scale(app, spec)
            except Exception as e:
                self.logger.warning(f"autoscale up blocked: {e}")
        elif (
            avg_load < spec.target_load / 2
            and depth == 0
            and len(healthy) > spec.min_replicas
        ):
            # only a fully idle replica may be stopped (in-flight
            # requests must never be cut); prefer the youngest so
            # long-warm replicas with populated caches survive
            idle = [r for r in healthy if r.load == 0.0]
            if idle:
                victim = idle[-1]
                self.logger.info(
                    f"autoscale DOWN {app.app_id}/{spec.name} "
                    f"({victim.replica_id})"
                )
                app.replicas[spec.name].remove(victim)
                await self._retire_replica(victim)
                self._journal_scale(app, spec)

    def _journal_scale(self, app: AppDeployment, spec: DeploymentSpec) -> None:
        """Autoscale verdicts are intent changes: the journaled replica
        target moves with them so a crash after a scale-up recovers to
        the SCALED size, not the deploy-time one. Journaled at intent
        commit (the scale happened) — never per request."""
        spec.num_replicas = len(app.replicas.get(spec.name, []))
        self._journal_append(
            "scale",
            {
                "app_id": app.app_id,
                "deployment": spec.name,
                "num_replicas": spec.num_replicas,
            },
        )

    async def _autoscale_predictive(
        self,
        app: AppDeployment,
        spec: DeploymentSpec,
        scheduler: DeploymentScheduler,
        healthy: list,
        replicas: list,
    ) -> None:
        """Scheduler-backed deployments scale on the predictor's
        verdict: up when measured arrival rate x service time projects
        a wait over the threshold (BEFORE queues saturate — occupancy
        alone reacts after), down only after the configured hysteresis
        of consecutive idle verdicts, riding the same drain machinery
        as undeploy so in-flight work is never cut."""
        decision, proj = scheduler.scale_decision(len(healthy))
        if decision == "up" and len(replicas) < spec.max_replicas:
            self.logger.info(
                f"predictive autoscale UP {app.app_id}/{spec.name} "
                f"(projected_wait={proj['projected_wait_s']:.3f}s, "
                f"utilization={proj['utilization']:.2f}, "
                f"rate={proj['arrival_rate']:.1f}/s)"
            )
            try:
                await self._add_replica(app, spec)
                self._replicas_changed.set()
                self._journal_scale(app, spec)
            except Exception as e:  # noqa: BLE001 — capacity may come later
                self.logger.warning(f"predictive autoscale up blocked: {e}")
        elif decision == "down" and len(healthy) > spec.min_replicas:
            # only a fully idle replica may be retired; prefer the
            # youngest so long-warm program caches survive
            idle = [r for r in healthy if r.load == 0.0]
            if idle:
                victim = idle[-1]
                self.logger.info(
                    f"predictive autoscale DOWN {app.app_id}/{spec.name} "
                    f"({victim.replica_id})"
                )
                app.replicas[spec.name].remove(victim)
                await self._retire_replica(victim)
                self._journal_scale(app, spec)

    # ---- status -------------------------------------------------------------

    def get_app_status(self, app_id: str) -> dict:
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        return {
            "app_id": app_id,
            "status": app.status,
            "created_at": app.created_at,
            # the fencing epoch + phase: `bioengine apps status` shows
            # these so an operator can watch a reconcile converge
            "controller": {
                "epoch": self.epoch,
                "phase": self.phase,
                "reconcile": self.reconcile_report,
            },
            # the scale-out router tier: table version/epoch plus each
            # router's last-reported sync (acked version, staleness age)
            "router_tier": self.router_publisher.describe(),
            "cost": self._cost_rollup(app_id),
            "deployments": {
                name: self._describe_deployment(app_id, name, replicas)
                for name, replicas in app.replicas.items()
            },
        }

    def _cost_rollup(self, app_id: str) -> dict:
        """Per-app chip-seconds from the process registry — the feature
        vector the future scheduler consumes (ROADMAP item 1). Replicas
        in THIS process (local placement, or the in-process multi-host
        harness) account here; separate worker-host processes surface
        their slice via their own ``get_metrics``/``get_flight_record``
        and the incident bundle."""
        total = 0.0
        by_dep: dict[str, dict] = {}
        for key, child in CHIP_SECONDS.items():
            a, dep, method = key
            if a != app_id:
                continue
            v = child.value
            total += v
            d = by_dep.setdefault(
                dep, {"chip_seconds_total": 0.0, "by_method": {}}
            )
            d["chip_seconds_total"] = round(d["chip_seconds_total"] + v, 6)
            d["by_method"][method] = round(v, 6)
        return {
            "chip_seconds_total": round(total, 6),
            "by_deployment": by_dep,
        }

    def _describe_deployment(self, app_id, name, replicas) -> dict:
        """Per-deployment status: replica describes plus the load
        rollup least-loaded routing acts on — router queue depth,
        outstanding + parked calls, and each replica's mesh shape, so
        a sharded replica that hogs traffic (or idles its chips) is
        visible from one status call."""
        described = [r.describe() for r in replicas]
        # RemoteReplica.describe deliberately omits queued_requests
        # (the semaphore queue lives host-side): a missing key means
        # UNKNOWN, so the rollup reports None rather than coercing to
        # 0 and faking an idle queue to least-loaded routing decisions
        queued = [d.get("queued_requests") for d in described]
        scheduler = self._schedulers.get((app_id, name))
        pool = self._warm_pools.get((app_id, name))
        # per-deployment compile rollup from the replicas' engine
        # describes (joined on the same RuntimeDeployment._status_key
        # the pipeline/mesh views use): how many "compiles" were
        # persistent/tier cache hits vs real XLA work
        tier_hits = real_compiles = 0
        for d in described:
            for eng in ((d.get("mesh") or {}).get("engines") or {}).values():
                progs = eng.get("programs") or {}
                tier_hits += int(progs.get("persistent_hits") or 0)
                real_compiles += int(progs.get("real_compiles") or 0)
        # the newest replica's TTFR breakdown — the number the warm
        # path is accountable for, fresh from the latest scale-up
        last_ttfr = None
        for d in reversed(described):
            cold = d.get("cold_start") or {}
            if cold.get("ttfr_seconds") is not None:
                last_ttfr = cold
                break
        tracker = self._outliers.get((app_id, name))
        return {
            "num_replicas": len(replicas),
            "scheduler": scheduler.describe() if scheduler else None,
            # latency-outlier view (serving/outlier.py): per-replica
            # EWMAs vs the deployment median, probation flags, and the
            # p95-derived hedge delay — the evidence the gray-failure
            # runbook reads next to `bioengine slo status`
            "gray_failure": tracker.describe() if tracker else None,
            "cold_start": {
                "warm_pool": pool.stats() if pool else None,
                "last_replica_ttfr": last_ttfr,
                "compile": {
                    "persistent_cache_hits": tier_hits,
                    "real_compiles": real_compiles,
                    "hit_rate": round(
                        tier_hits / (tier_hits + real_compiles), 4
                    )
                    if (tier_hits + real_compiles)
                    else None,
                },
            },
            "replicas": described,
            "queue_depth": self._queue_depth.get((app_id, name), 0),
            "outstanding_calls": sum(
                d.get("ongoing_requests", 0) for d in described
            ),
            "queued_calls": (
                sum(queued) if all(q is not None for q in queued) else None
            ),
            "avg_load": round(
                sum(d.get("load", 0.0) for d in described) / len(described),
                4,
            ) if described else 0.0,
            "mesh_shapes": {
                d["replica_id"]: (d.get("mesh") or {}).get("mesh_shape")
                for d in described
                if d.get("mesh")
            },
            # one-logical-deployment-over-many-hosts view: per-replica
            # shard placement + the cross-shard transfer rate (the
            # number that says whether the pipeline split is
            # transfer-bound); None when no replica is a mesh
            "cross_host_mesh": {
                d["replica_id"]: {
                    "kind": (d["mesh"] or {}).get("kind"),
                    "mesh_shape": (d["mesh"] or {}).get("mesh_shape"),
                    "cross_host": (d["mesh"] or {}).get("cross_host"),
                    "hosts": (d["mesh"] or {}).get("hosts"),
                    "shards": (d["mesh"] or {}).get("shards"),
                    "transfer": (d["mesh"] or {}).get("transfer"),
                }
                for d in described
                if (d.get("mesh") or {}).get("shards") is not None
            }
            or None,
        }

    # ---- telemetry / SLO surfaces -------------------------------------------

    def get_telemetry(
        self,
        series: Any = None,
        app: Optional[str] = None,
        deployment: Optional[str] = None,
        since: Optional[float] = None,
        resolution: Optional[float] = None,
    ) -> dict:
        """Reconstructed per-deployment series from the telemetry
        store (rates, latency quantiles from merged buckets, queue
        depth, chip-seconds, shed counts). ``series`` is one name, a
        list, or None for all; ``resolution`` picks a ring (seconds,
        next-coarser match), ``since`` a wall-clock cursor. Only LIVE
        history is reported — undeploy sweeps a deployment's series."""
        if isinstance(series, str):
            names = [series]
        else:
            names = list(series) if series else list(SERIES_NAMES)
        unknown = sorted(set(names) - set(SERIES_NAMES))
        if unknown:
            raise ValueError(
                f"unknown telemetry series {unknown} "
                f"(available: {list(SERIES_NAMES)})"
            )
        store = self.telemetry
        out: dict[str, Any] = {
            "generated_at": time.time(),
            "store": store.describe(),
            "deployments": {},
        }
        for a, d in store.keys():
            if app is not None and a != app:
                continue
            if deployment is not None and d != deployment:
                continue
            out["deployments"][f"{a}/{d}"] = {
                name: store.series(
                    a, d, name, since=since, resolution=resolution
                )
                for name in names
            }
        return out

    def get_slo_status(self) -> dict:
        """Burn rates, budget remaining, and alert state per tracked
        deployment, plus metadata of auto-captured incident bundles —
        JSON-able (this is the ``get_slo_status`` verb body and the
        ``bioengine slo status`` CLI feed)."""
        status = self.slo.status()
        status["auto_bundles"] = [
            {
                "generated_at": b.get("generated_at"),
                "alert": b.get("slo_alert"),
                "events": len(b.get("events", [])),
            }
            for b in self.slo_bundles
        ]
        return status

    def _slo_page_hook(self, alert: dict) -> None:
        """A page-severity SLO firing: snapshot the flight ring NOW
        (evidence survives even if the bundle task is starved), then
        capture a full cross-host incident bundle in the background —
        rate-limited per deployment so a flapping alert cannot DoS the
        hosts with bundle gathering."""
        key = (alert.get("app", ""), alert.get("deployment", ""))
        interval = float(
            os.environ.get("BIOENGINE_SLO_BUNDLE_INTERVAL_S", "300")
        )
        now = time.monotonic()
        last = self._slo_bundle_last.get(key)
        if last is not None and now - last < interval:
            return
        self._slo_bundle_last[key] = now
        flight.dump(
            "slo_page",
            app=alert.get("app"),
            deployment=alert.get("deployment"),
            objective=alert.get("objective"),
        )
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync unit test) — the dump above is the artifact
        from bioengine_tpu.utils.tasks import spawn_supervised

        spawn_supervised(
            self._capture_slo_bundle(alert),
            name="slo-auto-bundle",
            logger=self.logger,
        )

    async def _capture_slo_bundle(self, alert: dict) -> None:
        try:
            bundle = await self.debug_bundle()
        except Exception as e:  # noqa: BLE001 — bundling never breaks serving
            self.logger.error(f"slo auto-bundle failed: {e}")
            return
        bundle["slo_alert"] = alert
        self.slo_bundles.append(bundle)
        flight.record(
            "slo.bundle",
            app=alert.get("app"),
            deployment=alert.get("deployment"),
            objective=alert.get("objective"),
            events=len(bundle.get("events", [])),
        )
        target_dir = os.environ.get("BIOENGINE_FLIGHT_DIR")
        if target_dir:
            import json as _json
            from pathlib import Path as _Path

            def _write() -> None:
                try:
                    path = _Path(target_dir).expanduser()
                    path.mkdir(parents=True, exist_ok=True)
                    stamp = time.strftime("%Y%m%d-%H%M%S")
                    name = (
                        f"slo-bundle-{stamp}-{alert.get('app')}"
                        f"-{alert.get('deployment')}.json"
                    )
                    (path / name).write_text(
                        _json.dumps(bundle, indent=2, default=str)
                    )
                except OSError as e:
                    self.logger.warning(f"slo bundle not persisted: {e}")

            await asyncio.get_running_loop().run_in_executor(None, _write)

    async def debug_bundle(
        self,
        event_limit: int = 2000,
        max_spans: int = 1000,
        host_timeout_s: float = 10.0,
    ) -> dict:
        """One time-merged incident artifact: this process's flight
        record, recent traces, and metrics snapshot, plus the flight
        record + metrics + describe (topology, replica/mesh state) of
        every REACHABLE worker host, with all flight events folded into
        a single wall-clock-ordered timeline (deduped by recorder
        identity, so an in-process harness where hosts share this
        process's ring never double-reports). Unreachable hosts are
        reported as such instead of failing the bundle — the hosts you
        can't reach are usually the ones the incident is about."""
        local_rec = flight.get_record(limit=event_limit)
        records = [local_rec]
        hosts_out: dict[str, Any] = {}

        async def gather_host(host) -> None:
            # the three verbs (and the hosts) are independent — run
            # them concurrently so a cluster with several wedged hosts
            # costs ONE timeout, not hosts x verbs of them; the bundle
            # is the tool an operator reaches for mid-incident
            try:
                rec, met, desc = await asyncio.gather(
                    self._call_host(
                        host.service_id,
                        "get_flight_record",
                        limit=event_limit,
                        rpc_timeout=host_timeout_s,
                    ),
                    self._call_host(
                        host.service_id, "get_metrics",
                        rpc_timeout=host_timeout_s,
                    ),
                    self._call_host(
                        host.service_id, "describe",
                        rpc_timeout=host_timeout_s,
                    ),
                )
                # skew: prefer the host's own latest handshake estimate
                # (stamped on its record), fall back to what it reported
                # at registration — either way the merged timeline below
                # is corrected onto the controller's clock
                if "clock_skew_s" not in rec:
                    rec["clock_skew_s"] = host.clock_skew_s
                records.append(rec)
                hosts_out[host.host_id] = {
                    "reachable": True,
                    "recorder": rec.get("recorder"),
                    "flight_events": len(rec.get("events", []) or []),
                    "dumps": rec.get("dumps", []),
                    "clock_skew_s": rec.get("clock_skew_s", 0.0),
                    "metrics": met,
                    "describe": desc,
                }
            except Exception as e:  # noqa: BLE001 — partial bundle beats none
                hosts_out[host.host_id] = {
                    "reachable": False,
                    "reason": f"{type(e).__name__}: {e}",
                }

        live_hosts = []
        for host in list(self.cluster_state.hosts.values()):
            if host.alive:
                live_hosts.append(host)
            else:
                hosts_out[host.host_id] = {
                    "reachable": False,
                    "reason": "marked dead",
                }
        await asyncio.gather(*(gather_host(h) for h in live_hosts))
        return {
            "generated_at": time.time(),
            "recorder": local_rec["recorder"],
            "events": flight.merge_records(records),
            "dumps": local_rec["dumps"],
            "traces": tracing.get_spans(
                max_spans=max_spans, include_open=True
            ),
            "metrics": metrics.collect(),
            "slo": self.slo.status(),
            "controller": {
                "epoch": self.epoch,
                "phase": self.phase,
                "reconcile": self.reconcile_report,
            },
            "journal": (
                self.journal.describe() if self.journal is not None else None
            ),
            "compile_tier": self.compile_tier.stats(),
            "telemetry": self.telemetry.describe(),
            "cluster": self.cluster_state.snapshot(),
            "apps": {
                app_id: self.get_app_status(app_id)
                for app_id in list(self.apps)
            },
            "hosts": hosts_out,
        }

    def list_apps(self) -> list[str]:
        return sorted(self.apps)

    def get_load(self, app_id: str) -> float:
        app = self.apps.get(app_id)
        if not app:
            return 0.0
        loads = [
            r.load
            for replicas in app.replicas.values()
            for r in replicas
            if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        return sum(loads) / len(loads) if loads else 0.0
