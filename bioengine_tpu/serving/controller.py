"""ServeController — deployment orchestration, health loop, autoscaling.

Replaces Ray Serve as used by the reference (serve.run per app with
autoscaling 1-10 replicas and health-check-driven restarts, ref
bioengine/apps/proxy_deployment.py:25-47, bioengine/apps/manager.py:
355-455). Differences by design:

- Load is measured at the controller (per-replica semaphore occupancy +
  queue depth), so the reference's "mimic request" workaround for the
  Serve autoscaler (proxy_deployment.py:405-442) has no equivalent —
  the signal is native.
- Replicas scale in whole units, each owning a fixed chip set leased
  from ClusterState; unplaceable replicas enqueue a pending workload,
  which is exactly what drives the provisioner's scale-up
  (cluster/provisioner.py check_scaling).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.serving.remote import RemoteReplica
from bioengine_tpu.serving.replica import Replica, ReplicaState
from bioengine_tpu.utils.logger import create_logger


@dataclass
class DeploymentSpec:
    name: str
    instance_factory: Callable[[], Any]
    num_replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 3
    chips_per_replica: int = 0
    max_ongoing_requests: int = 10
    autoscale: bool = True
    target_load: float = 0.7          # scale up above, down below half
    # artifact payload (manifest + sources + kwargs) for building this
    # deployment on a REMOTE worker host — set by AppBuilder; None means
    # the deployment can only be placed locally
    remote_payload: Optional[dict] = None


@dataclass
class AppDeployment:
    app_id: str
    specs: dict[str, DeploymentSpec]
    replicas: dict[str, list[Replica]] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)
    status: str = "DEPLOYING"         # DEPLOYING | RUNNING | UNHEALTHY | DEPLOY_FAILED | STOPPED
    # per-method ACL for cross-host route_call — same shape as the app
    # proxy's authorized_users (list = all methods, dict = per-method).
    # None means "no ACL recorded": route_call then admits admins only.
    acl: Any = None


class DeploymentHandle:
    """Client-side handle: route calls to healthy replicas (least-loaded,
    round-robin tie-break). The composition mechanism: entry deployments
    receive handles to their sibling deployments as init kwargs, same as
    the reference's DeploymentHandle binding (ref apps/builder.py:1474-1508)."""

    def __init__(self, controller: "ServeController", app_id: str, deployment: str):
        self._controller = controller
        self.app_id = app_id
        self.deployment = deployment
        self._rr = itertools.count()

    async def call(self, method: str, *args, **kwargs) -> Any:
        replica = self._controller._pick_replica(self.app_id, self.deployment)
        self._controller._queue_depth[(self.app_id, self.deployment)] += 1
        try:
            return await replica.call(method, *args, **kwargs)
        finally:
            self._controller._queue_depth[(self.app_id, self.deployment)] -= 1

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def invoke(*args, **kwargs):
            return await self.call(name, *args, **kwargs)

        invoke.__name__ = name
        return invoke


class ServeController:
    def __init__(
        self,
        cluster_state: Optional[ClusterState] = None,
        health_check_period: float = 10.0,
        log_file: Optional[str] = None,
    ):
        self.cluster_state = cluster_state or ClusterState()
        self.health_check_period = health_check_period
        self.apps: dict[str, AppDeployment] = {}
        self.logger = create_logger("serving", log_file=log_file)
        self._health_task: Optional[asyncio.Task] = None
        self._queue_depth: dict[tuple[str, str], int] = defaultdict(int)
        self._rr_counters: dict[tuple[str, str], itertools.count] = {}
        self._rpc_server = None            # set by attach_rpc (multi-host)
        self._router_admins: list[str] = []

    # ---- multi-host control plane -------------------------------------------

    def attach_rpc(self, server, admin_users: Optional[list[str]] = None) -> None:
        """Enable multi-host placement: registers the ``serve-router``
        service that (a) worker hosts join through (``register_host``)
        and (b) remote deployments route composition calls back through
        (``route_call`` — the cross-host analog of a Serve
        DeploymentHandle call, ref apps/builder.py:1474-1508)."""
        from bioengine_tpu.utils.permissions import (
            check_method_permission,
            check_permissions,
            is_authorized,
        )

        self._rpc_server = server
        self._router_admins = list(admin_users or [])

        async def route_call(
            app_id, deployment, method, args=None, kwargs=None, context=None
        ):
            # Same per-method ACL the front-door proxy enforces
            # (apps/proxy.py) — route_call must not be a side door.
            # Admins (incl. worker hosts holding the admin token, whose
            # composition handles route through here) always pass.
            if not is_authorized(context, self._router_admins):
                app = self.apps.get(app_id)
                acl = app.acl if app is not None else None
                check_method_permission(acl or [], method, context)
            handle = self.get_handle(app_id, deployment)
            return await handle.call(method, *(args or []), **(kwargs or {}))

        def register_host(
            host_id, service_id, topology, worker_tag=None, context=None
        ):
            check_permissions(context, self._router_admins, "register_host")
            self.cluster_state.register_host(
                host_id, service_id, topology, worker_tag
            )
            self.logger.info(
                f"host '{host_id}' joined with "
                f"{topology.get('n_chips', 0)} chips ({service_id})"
            )
            return {"host_id": host_id, "registered": True}

        def deregister_host(host_id, context=None):
            check_permissions(context, self._router_admins, "deregister_host")
            orphans = self.cluster_state.mark_host_dead(host_id)
            return {"host_id": host_id, "orphaned_replicas": orphans}

        server.register_local_service(
            {
                "id": "serve-router",
                "name": "Serving controller router",
                "type": "bioengine-serve-router",
                # public visibility: every method self-enforces
                # (register/deregister_host require admin; route_call
                # enforces the target app's per-method ACL above)
                "config": {"require_context": True, "visibility": "public"},
                "route_call": route_call,
                "register_host": register_host,
                "deregister_host": deregister_host,
            }
        )

    async def _call_host(
        self,
        service_id: str,
        method: str,
        *args,
        rpc_timeout: Optional[float] = None,
        **kwargs,
    ):
        if self._rpc_server is None:
            raise RuntimeError("controller has no RPC server attached")
        return await self._rpc_server.call_service_method(
            service_id, method, args, kwargs,
            **({"timeout": rpc_timeout} if rpc_timeout else {}),
        )

    # ---- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        if self._health_task is None:
            self._health_task = asyncio.create_task(self._health_loop())

    async def stop(self) -> None:
        if self._health_task:
            self._health_task.cancel()
            self._health_task = None
        for app_id in list(self.apps):
            await self.undeploy(app_id)

    # ---- deploy / undeploy --------------------------------------------------

    async def deploy(
        self, app_id: str, specs: list[DeploymentSpec], acl: Any = None
    ) -> AppDeployment:
        existing = self.apps.get(app_id)
        if existing is not None:
            if existing.status in ("DEPLOY_FAILED", "STOPPED"):
                del self.apps[app_id]  # failed attempt may be retried
            else:
                raise ValueError(f"app '{app_id}' already deployed")
        app = AppDeployment(
            app_id=app_id, specs={s.name: s for s in specs}, acl=acl
        )
        self.apps[app_id] = app
        try:
            for spec in specs:
                app.replicas[spec.name] = []
                for _ in range(spec.num_replicas):
                    await self._add_replica(app, spec)
            app.status = "RUNNING"
            self.logger.info(f"app '{app_id}' deployed")
        except Exception:
            # Roll back partial state: stop started replicas and release
            # their chip leases so a failed deploy leaks nothing.
            app.status = "DEPLOY_FAILED"
            for replicas in app.replicas.values():
                for r in replicas:
                    try:
                        await r.stop()
                    finally:
                        self.cluster_state.mark_replica_dead(r.replica_id)
            raise
        return app

    async def _add_replica(self, app: AppDeployment, spec: DeploymentSpec):
        """Place one replica: locally when this host has the chips, else
        on a joined worker host with capacity (RPC-backed RemoteReplica),
        else enqueue a pending workload for the provisioner."""
        from bioengine_tpu.utils.tracing import span

        with span(
            "add_replica", app_id=app.app_id, deployment=spec.name,
            chips=spec.chips_per_replica,
        ):
            return await self._add_replica_inner(app, spec)

    async def _add_replica_inner(self, app: AppDeployment, spec: DeploymentSpec):
        replica = None
        host_id = None
        if spec.chips_per_replica > 0 and (
            self.cluster_state.free_chips() < spec.chips_per_replica
        ):
            replica = self._make_remote_replica(app, spec)
            if replica is None:
                # No capacity anywhere: surface as pending workload so
                # the provisioner can scale out (ref manager.py:239-353's
                # SLURM headroom allowance).
                self.cluster_state.add_pending(
                    f"{app.app_id}/{spec.name}",
                    {"chips": spec.chips_per_replica},
                )
                raise RuntimeError(
                    f"need {spec.chips_per_replica} chips for "
                    f"{app.app_id}/{spec.name}: none free locally or on "
                    f"any joined host"
                )
            host_id = replica.host_id
        if replica is None:
            replica = Replica(
                app_id=app.app_id,
                deployment_name=spec.name,
                instance_factory=spec.instance_factory,
                max_ongoing_requests=spec.max_ongoing_requests,
                log_sink=self.cluster_state.append_replica_log,
            )
            if spec.chips_per_replica > 0:
                replica.device_ids = self.cluster_state.acquire_chips(
                    replica.replica_id, spec.chips_per_replica
                )
        self.cluster_state.register_replica(
            app.app_id,
            spec.name,
            replica.replica_id,
            replica.device_ids,
            host_id=host_id,
        )
        try:
            await replica.start()
        except Exception:
            self.cluster_state.mark_replica_dead(replica.replica_id)
            app.replicas[spec.name].append(replica)
            raise
        app.replicas[spec.name].append(replica)
        self.cluster_state.remove_pending(f"{app.app_id}/{spec.name}")
        return replica

    def _make_remote_replica(
        self, app: AppDeployment, spec: DeploymentSpec
    ) -> Optional["RemoteReplica"]:
        if self._rpc_server is None or spec.remote_payload is None:
            return None
        self._prune_dead_hosts()  # never place on a host whose ws is gone
        host = self.cluster_state.find_host_for_chips(spec.chips_per_replica)
        if host is None:
            return None
        replica = RemoteReplica(
            app_id=app.app_id,
            deployment_name=spec.name,
            host_id=host.host_id,
            host_service_id=host.service_id,
            call_host=self._call_host,
            payload=spec.remote_payload,
            max_ongoing_requests=spec.max_ongoing_requests,
            log_sink=self.cluster_state.append_replica_log,
        )
        replica.device_ids = self.cluster_state.host_acquire_chips(
            host.host_id, replica.replica_id, spec.chips_per_replica
        )
        self.logger.info(
            f"placing {app.app_id}/{spec.name} on host '{host.host_id}' "
            f"(chips {replica.device_ids})"
        )
        return replica

    async def undeploy(self, app_id: str) -> None:
        app = self.apps.pop(app_id, None)
        if app is None:
            return
        for replicas in app.replicas.values():
            for r in replicas:
                await r.stop()
                self.cluster_state.mark_replica_dead(r.replica_id)
        app.status = "STOPPED"
        self.logger.info(f"app '{app_id}' undeployed")

    # ---- request routing ----------------------------------------------------

    def get_handle(self, app_id: str, deployment: Optional[str] = None) -> DeploymentHandle:
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        if deployment is None:
            deployment = next(iter(app.specs))
        if deployment not in app.specs:
            raise KeyError(f"app '{app_id}' has no deployment '{deployment}'")
        self._queue_depth.setdefault((app_id, deployment), 0)
        return DeploymentHandle(self, app_id, deployment)

    def _pick_replica(self, app_id: str, deployment: str) -> Replica:
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        healthy = [
            r
            for r in app.replicas.get(deployment, [])
            if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        if not healthy:
            raise RuntimeError(
                f"no healthy replicas for {app_id}/{deployment}"
            )
        min_load = min(r.load for r in healthy)
        candidates = [r for r in healthy if r.load == min_load]
        rr = self._rr_counters.setdefault(
            (app_id, deployment), itertools.count()
        )
        return candidates[next(rr) % len(candidates)]

    # ---- health + autoscaling loop ------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.health_check_period)
                await self.health_tick()
            except asyncio.CancelledError:
                return
            except Exception as e:
                self.logger.error(f"health loop error: {e}")

    async def health_tick(self) -> None:
        """One pass: health-check replicas, restart dead ones, autoscale."""
        self._prune_dead_hosts()
        for app in list(self.apps.values()):
            any_unhealthy = False
            for spec_name, spec in app.specs.items():
                replicas = app.replicas.get(spec_name, [])
                for r in list(replicas):
                    state = await r.check_health()
                    if state == ReplicaState.UNHEALTHY:
                        any_unhealthy = True
                        self.logger.warning(
                            f"restarting unhealthy replica {r.replica_id}"
                        )
                        await r.stop()
                        self.cluster_state.mark_replica_dead(r.replica_id)
                        replicas.remove(r)
                        try:
                            await self._add_replica(app, spec)
                        except Exception as e:
                            self.logger.error(
                                f"replica restart failed for "
                                f"{app.app_id}/{spec_name}: {e}"
                            )
                await self._autoscale(app, spec)
                alive = [
                    r
                    for r in app.replicas.get(spec_name, [])
                    if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING,
                                   ReplicaState.INITIALIZING)
                ]
                if not alive:
                    any_unhealthy = True
            app.status = "UNHEALTHY" if any_unhealthy else "RUNNING"

    def _prune_dead_hosts(self) -> None:
        """A host whose RPC service vanished (websocket closed) is dead:
        release its chip accounting so restarts can re-place its
        replicas. The replicas themselves go UNHEALTHY on their next
        check (transport error) and ride the normal restart path."""
        if self._rpc_server is None:
            return
        live_services = {
            s["id"] for s in self._rpc_server.list_services()
        }
        for host in list(self.cluster_state.hosts.values()):
            if host.alive and host.service_id not in live_services:
                orphans = self.cluster_state.mark_host_dead(host.host_id)
                self.logger.warning(
                    f"host '{host.host_id}' lost "
                    f"(orphaned replicas: {orphans})"
                )

    async def _autoscale(self, app: AppDeployment, spec: DeploymentSpec) -> None:
        if not spec.autoscale:
            return
        replicas = app.replicas.get(spec.name, [])
        # TESTING replicas carry real traffic (they are routable), so
        # they must count toward the load/scaling signal
        healthy = [
            r
            for r in replicas
            if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        if not healthy:
            return
        avg_load = sum(r.load for r in healthy) / len(healthy)
        depth = self._queue_depth.get((app.app_id, spec.name), 0)
        if (
            avg_load > spec.target_load or depth > len(healthy) * spec.max_ongoing_requests
        ) and len(replicas) < spec.max_replicas:
            self.logger.info(
                f"autoscale UP {app.app_id}/{spec.name} "
                f"(load={avg_load:.2f}, depth={depth})"
            )
            try:
                await self._add_replica(app, spec)
            except Exception as e:
                self.logger.warning(f"autoscale up blocked: {e}")
        elif (
            avg_load < spec.target_load / 2
            and depth == 0
            and len(healthy) > spec.min_replicas
        ):
            # only a fully idle replica may be stopped (in-flight
            # requests must never be cut); prefer the youngest so
            # long-warm replicas with populated caches survive
            idle = [r for r in healthy if r.load == 0.0]
            if idle:
                victim = idle[-1]
                self.logger.info(
                    f"autoscale DOWN {app.app_id}/{spec.name} "
                    f"({victim.replica_id})"
                )
                await victim.stop()
                self.cluster_state.mark_replica_dead(victim.replica_id)
                app.replicas[spec.name].remove(victim)

    # ---- status -------------------------------------------------------------

    def get_app_status(self, app_id: str) -> dict:
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        return {
            "app_id": app_id,
            "status": app.status,
            "created_at": app.created_at,
            "deployments": {
                name: {
                    "num_replicas": len(replicas),
                    "replicas": [r.describe() for r in replicas],
                    "queue_depth": self._queue_depth.get((app_id, name), 0),
                }
                for name, replicas in app.replicas.items()
            },
        }

    def list_apps(self) -> list[str]:
        return sorted(self.apps)

    def get_load(self, app_id: str) -> float:
        app = self.apps.get(app_id)
        if not app:
            return 0.0
        loads = [
            r.load
            for replicas in app.replicas.values()
            for r in replicas
            if r.state in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        return sum(loads) / len(loads) if loads else 0.0
