"""Mesh planning — placement POLICY for deployments bigger than one
host's lease, split from engine EXECUTION.

Every earlier serving layer places a REPLICA onto ONE host and stops at
the chips that host leases (PR 5 sharding, PR 8 scheduling, PR 11 warm
pools). This module plans one logical DEPLOYMENT across several hosts'
leases: a hardware-neutral :class:`MeshConfig` (the manifest's
``deployment_config.<dep>.mesh`` block) names the parallelism shape —
pipeline stages, per-stage chips, per-stage dp/tp axes — and
:func:`plan_mesh` maps it onto whatever topology is actually joined,
using the SAME pluggable cost-model contract the global scheduler's
replica placement rides (``ServeController.scorer_factory`` — the
feature dict is the interface, so a learned policy scores hosts the
day it scores replicas).

Topology portability (VirtualFlow's virtual-device decoupling, Maple's
portable-across-clusters placement — PAPERS.md): the same spec resolves
to

- one host with enough chips → all stages colocate there (the warm-
  affinity bonus pulls them together; activations still hop through the
  RPC plane, but loopback),
- several small hosts → stages span them, activations crossing hosts on
  the PR 3 zero-copy OOB transport,
- a forced-host-device CPU mesh → the same plan, exercised hermetically.

Execution lives in :mod:`bioengine_tpu.serving.mesh_replica`
(``CrossHostEngine`` + ``MeshReplica``); this module never touches a
device.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

MESH_KINDS = ("pipeline", "dp", "tp")


class MeshPlanError(RuntimeError):
    """No plan satisfies the spec on the currently-joined topology.
    Carries ``chips_needed`` so the controller can enqueue a pending
    workload sized for the WHOLE mesh (the provisioner's scale-up
    signal), not one replica's slice."""

    def __init__(self, message: str, chips_needed: int = 0):
        super().__init__(message)
        self.chips_needed = chips_needed


@dataclass
class MeshConfig:
    """Hardware-neutral multi-host mesh spec (manifest:
    ``deployment_config.<dep>.mesh``).

    ``stages`` is the cross-host axis: each stage lands on (up to) one
    host's lease of ``chips_per_stage`` chips and holds ONLY its slice
    of the model — the axis that serves checkpoints bigger than any
    single lease. ``kind`` names how the driver composes shard outputs:

    - ``pipeline`` — stage k+1 consumes stage k's activations
      (sequential hops; the shard contract is
      ``stage_method(stage, inputs)`` returning the activation array),
    - ``dp`` — every shard holds the full model; the batch splits
      across shards and outputs concatenate,
    - ``tp`` — every shard computes a partial output from the full
      input; the driver sums (the host-mediated all-reduce of the
      Megatron two-layer block).

    ``axes`` is the PER-STAGE virtual-device spec resolved over each
    shard's concrete lease (parallel/mesh.py ``VirtualMeshSpec``), so
    within-host dp/tp ride the PR 5 engine unchanged. ``entry_methods``
    are the instance methods the mesh driver intercepts and fans across
    shards; everything else routes to stage 0.
    """

    stages: int = 2
    chips_per_stage: int = 1
    kind: str = "pipeline"
    axes: dict = field(default_factory=lambda: {"dp": -1})
    stage_method: str = "run_stage"
    entry_methods: tuple = ("predict",)
    # per-stage-hop budget; None defers to BIOENGINE_MESH_STAGE_TIMEOUT_S
    stage_timeout_s: Optional[float] = None
    # when only one capable host remains, re-plans may colocate every
    # stage there (degraded but serving) — 0 disables the fallback and
    # keeps the deployment down until a second host joins
    single_host_fallback: bool = True

    @classmethod
    def from_config(cls, cfg: dict) -> "MeshConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(
                f"unknown mesh config keys: {unknown} "
                f"(accepted: {sorted(known)})"
            )
        out = cls()
        if "stages" in cfg:
            out.stages = int(cfg["stages"])
            if out.stages < 1:
                raise ValueError("mesh.stages must be >= 1")
        if "chips_per_stage" in cfg:
            out.chips_per_stage = int(cfg["chips_per_stage"])
            if out.chips_per_stage < 1:
                raise ValueError("mesh.chips_per_stage must be >= 1")
        if "kind" in cfg:
            out.kind = str(cfg["kind"])
            if out.kind not in MESH_KINDS:
                raise ValueError(
                    f"mesh.kind '{out.kind}' not in {list(MESH_KINDS)}"
                )
        if "axes" in cfg:
            axes = dict(cfg["axes"])
            for k, v in axes.items():
                if k not in ("dp", "tp"):
                    # the engine's virtual-device layer shards batches
                    # over dp and weights over tp; any other name (or a
                    # typo) would pass here only to fail every shard
                    # start at deploy time
                    raise ValueError(
                        f"mesh.axes names unsupported axis {k!r} "
                        "(per-stage axes are 'dp' and 'tp'; the stage "
                        "axis is 'stages')"
                    )
                if int(v) != -1 and int(v) < 1:
                    # -1 = fill; anything else must be a real width
                    # (negative sizes survive Python's modulo inside
                    # MeshSpec.resolve and would silently clamp to an
                    # unsharded engine downstream)
                    raise ValueError(
                        f"mesh.axes entry {k!r}: {v!r} invalid "
                        "(use -1 to fill, or a positive size)"
                    )
            out.axes = {k: int(v) for k, v in axes.items()}
        if "stage_method" in cfg:
            out.stage_method = str(cfg["stage_method"])
        if "entry_methods" in cfg:
            methods = cfg["entry_methods"]
            if isinstance(methods, str):
                methods = [methods]
            out.entry_methods = tuple(str(m) for m in methods)
            if not out.entry_methods:
                raise ValueError("mesh.entry_methods must not be empty")
        if "stage_timeout_s" in cfg and cfg["stage_timeout_s"] is not None:
            out.stage_timeout_s = float(cfg["stage_timeout_s"])
            if out.stage_timeout_s <= 0:
                raise ValueError("mesh.stage_timeout_s must be > 0")
        if "single_host_fallback" in cfg:
            out.single_host_fallback = bool(cfg["single_host_fallback"])
        # the axes spec must actually resolve over one stage's lease —
        # catching it here keeps the failure typed at BUILD time instead
        # of a raw ValueError at shard-engine construction (or worse,
        # from mesh_shape() inside a later get_app_status)
        try:
            out.mesh_shape()
        except ValueError as e:
            raise ValueError(
                f"mesh.axes {out.axes} do not resolve over "
                f"chips_per_stage={out.chips_per_stage}: {e}"
            ) from e
        return out

    @property
    def total_chips(self) -> int:
        return self.stages * self.chips_per_stage

    def resolved_stage_timeout_s(self) -> Optional[float]:
        if self.stage_timeout_s is not None:
            return self.stage_timeout_s
        raw = os.environ.get("BIOENGINE_MESH_STAGE_TIMEOUT_S", "")
        return float(raw) if raw else None

    def mesh_shape(self, n_devices_per_stage: Optional[int] = None) -> dict:
        """Logical shape for status surfaces: the stage axis plus the
        per-stage axes resolved over one lease."""
        from bioengine_tpu.parallel.mesh import VirtualMeshSpec

        return VirtualMeshSpec(stages=self.stages, axes=self.axes).shape(
            n_devices_per_stage or self.chips_per_stage
        )


@dataclass
class ShardAssignment:
    """One stage of the plan pinned to a host. ``device_ids`` is filled
    when the controller leases the chips (plan first, lease second —
    the plan itself is side-effect free)."""

    stage: int
    host_id: str
    service_id: str
    n_chips: int
    device_ids: list[int] = field(default_factory=list)


@dataclass
class MeshPlan:
    config: MeshConfig
    shards: list[ShardAssignment]

    @property
    def hosts(self) -> list[str]:
        return sorted({s.host_id for s in self.shards})

    @property
    def cross_host(self) -> bool:
        return len(self.hosts) > 1

    def describe(self) -> dict:
        return {
            "kind": self.config.kind,
            "mesh_shape": self.config.mesh_shape(),
            "cross_host": self.cross_host,
            "hosts": self.hosts,
            "shards": [
                {
                    "stage": s.stage,
                    "host_id": s.host_id,
                    "n_chips": s.n_chips,
                    "device_ids": list(s.device_ids),
                }
                for s in self.shards
            ],
        }


def plan_mesh(
    config: MeshConfig,
    hosts: Iterable,
    scorer,
    avoid_hosts: Iterable[str] = (),
) -> MeshPlan:
    """Place every stage of ``config`` onto ``hosts`` (HostRecord-shaped:
    ``host_id`` / ``service_id`` / ``n_chips`` / ``free_chip_ids()``).

    Stage by stage, each candidate host is scored through the SAME
    feature-dict contract the global scheduler's replica placement uses
    (lower wins). ``load`` is the host's chip occupancy counting the
    chips THIS plan already took from it; ``signature_affinity`` marks
    a host that already carries one of this plan's stages — the warm-
    colocation pull that collapses the whole mesh onto one big host
    when it fits (activation hops stay loopback), while capacity
    naturally forces spanning when it doesn't. ``avoid_hosts`` carries
    hosts the current incident implicates (a degrade-triggered re-plan
    passes the dead host).
    """
    avoid = set(avoid_hosts)
    candidates = list(hosts)
    planned: dict[str, int] = {}           # host_id -> chips taken so far
    shards: list[ShardAssignment] = []
    for stage in range(config.stages):
        exclude: set[str] = set()
        if (
            config.stages > 1
            and not config.single_host_fallback
            and stage == config.stages - 1
            and len(planned) == 1
        ):
            # the operator declared the model does NOT fit one host
            # (e.g. per-host HBM would be oversubscribed even though
            # the chip count works out). Spanning must be a HARD
            # constraint, not a score nudge — affinity OR plain load
            # asymmetry could otherwise pull the last stage onto the
            # one host that already holds every other stage, and a
            # post-hoc rejection would refuse a deployment whose
            # spanning plan is feasible.
            exclude = set(planned)
        best = None
        best_score = None
        for h in candidates:
            if h.host_id in exclude:
                continue
            free = len(h.free_chip_ids()) - planned.get(h.host_id, 0)
            if free < config.chips_per_stage:
                continue
            features = {
                "load": (h.n_chips - free) / max(1, h.n_chips),
                "queued": 0,
                "max_ongoing": h.n_chips,
                "breaker_failures": 0,
                "signature_affinity": planned.get(h.host_id, 0) > 0,
                "avoided": h.host_id in avoid,
                "group_size": config.chips_per_stage,
            }
            s = scorer.score(features)
            if best_score is None or s < best_score:
                best, best_score = h, s
        if best is None:
            if exclude:
                raise MeshPlanError(
                    f"all {config.stages} stages would colocate on "
                    f"'{next(iter(exclude))}' but "
                    f"mesh.single_host_fallback is off and no second "
                    f"host has {config.chips_per_stage} free chips",
                    chips_needed=config.total_chips,
                )
            raise MeshPlanError(
                f"stage {stage}/{config.stages}: no joined mesh-capable "
                f"host has {config.chips_per_stage} free chips "
                f"(need {config.total_chips} total across "
                f"{config.stages} stages)",
                chips_needed=config.total_chips,
            )
        planned[best.host_id] = (
            planned.get(best.host_id, 0) + config.chips_per_stage
        )
        shards.append(
            ShardAssignment(
                stage=stage,
                host_id=best.host_id,
                service_id=best.service_id,
                n_chips=config.chips_per_stage,
            )
        )
    return MeshPlan(config=config, shards=shards)
