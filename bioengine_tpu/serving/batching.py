"""Continuous batching for inference replicas.

Named in the north star (BASELINE.json: "route inference requests to TPU
replicas with continuous batching") and absent from the reference, which
forwards each request individually to the torch pipeline
(ref apps/model-runner/runtime_deployment.py:234-312).

Requests accumulate in an async queue; a drainer groups them by a
caller-provided signature (e.g. model id + shape bucket) and invokes the
batch function once per group. Groups close when ``max_batch`` is
reached or ``max_wait_ms`` elapses since the group's first request —
latency is bounded while the TPU sees large batches. Pairs with the
shape-bucketed InferenceEngine: batching by bucket signature means one
compiled program per flush.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Hashable, Optional

from bioengine_tpu.utils import metrics, tracing
from bioengine_tpu.utils.tasks import spawn_supervised

# One source for the batching knob defaults: the in-replica batcher,
# the operator-facing manifest knobs (deployment_config.<dep>.batching,
# surfaced through DeploymentSpec and injected as
# ``instance.bioengine_batch_config``), and the controller scheduler's
# cross-replica groups all read these instead of re-hardcoding.
DEFAULT_MAX_BATCH = 8
DEFAULT_MAX_WAIT_MS = 10.0


@dataclass
class PendingRequest:
    payload: Any
    future: asyncio.Future
    enqueued_at: float = field(default_factory=time.monotonic)
    # sampled-trace identity captured at submit: queue-wait is only
    # measurable at flush time, so the span is recorded retroactively
    # against the submitter's trace (None when unsampled — free).
    # parent_span is the submitter's enclosing span (replica.execute)
    # — the flush task's contextvars can't provide it
    trace_ctx: Any = None
    parent_span: Optional[str] = None


def _collect_batchers(instances: list) -> list:
    """Fold live ContinuousBatcher stats into process metrics: request
    and batch counters plus queue-wait quantiles. The stats dict stays
    the one bookkeeper; this is a scrape-time reader."""
    requests = batches = batched = 0
    waits: list[float] = []
    occupancy: list[int] = []
    for b in instances:
        requests += b._stats["requests"]
        batches += b._stats["batches"]
        batched += b._stats["batched_requests"]
        waits.extend(b._wait_samples)
        occupancy.extend(b._occupancy_samples)
    out = [
        metrics.Sample(
            "batcher_requests_total", requests, kind="counter",
            help="requests submitted to continuous batchers",
        ),
        metrics.Sample(
            "batcher_batches_total", batches, kind="counter",
            help="batch flushes executed",
        ),
        metrics.Sample(
            "batcher_batched_requests_total", batched, kind="counter",
            help="requests served through a batched flush",
        ),
    ]
    if waits:
        waits.sort()
        out.append(
            metrics.Sample(
                "batcher_queue_wait_ms",
                round(1000 * waits[len(waits) // 2], 3),
                {"quantile": "p50"},
                help="recent queue wait before flush",
            )
        )
        out.append(
            metrics.Sample(
                "batcher_queue_wait_ms",
                round(
                    1000
                    * waits[min(int(len(waits) * 0.95), len(waits) - 1)],
                    3,
                ),
                {"quantile": "p95"},
                help="recent queue wait before flush",
            )
        )
    if occupancy:
        # per-flush group size over a recent window — how full the
        # batches the TPU actually saw were (the throughput half of the
        # batching trade; queue_wait is the latency half)
        occupancy.sort()
        for q, idx in (
            ("p50", len(occupancy) // 2),
            ("p95", min(int(len(occupancy) * 0.95), len(occupancy) - 1)),
        ):
            out.append(
                metrics.Sample(
                    "batcher_occupancy",
                    occupancy[idx],
                    {"quantile": q},
                    help="recent per-flush batch size",
                )
            )
        out.append(
            metrics.Sample(
                "batcher_occupancy",
                round(sum(occupancy) / len(occupancy), 3),
                {"quantile": "mean"},
                help="recent per-flush batch size",
            )
        )
    return out


_BATCHERS = metrics.InstanceSet("continuous_batcher", _collect_batchers)


BatchFn = Callable[[Hashable, list[Any]], Awaitable[list[Any]]]


class ContinuousBatcher:
    """``submit(signature, payload)`` -> awaitable per-request result.

    ``batch_fn(signature, payloads) -> results`` runs once per flushed
    group; results map 1:1 onto payload order.
    """

    def __init__(
        self,
        batch_fn: BatchFn,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self._groups: dict[Hashable, list[PendingRequest]] = {}
        self._flush_tasks: dict[Hashable, asyncio.Task] = {}
        self._inflight_flushes: set[asyncio.Task] = set()
        self._stats = {"requests": 0, "batches": 0, "batched_requests": 0}
        # queue-wait samples (seconds), recorded per request at group
        # flush; bounded so stats cost stays flat under load
        self._wait_samples: deque[float] = deque(maxlen=1024)
        # per-flush group sizes over the same bounded window — the
        # occupancy histogram GET /metrics serves as batcher_occupancy
        self._occupancy_samples: deque[int] = deque(maxlen=1024)
        self._closed = False
        _BATCHERS.add(self)

    async def submit(self, signature: Hashable, payload: Any) -> Any:
        if self._closed:
            raise RuntimeError("batcher is closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        group = self._groups.setdefault(signature, [])
        ctx = tracing.current_trace()
        sampled = ctx is not None and ctx.sampled
        group.append(
            PendingRequest(
                payload,
                fut,
                trace_ctx=ctx if sampled else None,
                parent_span=tracing.current_span_id() if sampled else None,
            )
        )
        self._stats["requests"] += 1
        if len(group) >= self.max_batch:
            self._cancel_timer(signature)
            # NEVER run the flush inside the submitting coroutine: if
            # this submitter is cancelled while batch_fn is mid-flight,
            # the cancellation would kill the batch and strand every
            # other future in the group. A supervised task's lifetime
            # is independent of any one submitter.
            self._spawn_flush(signature)
        elif signature not in self._flush_tasks:
            self._flush_tasks[signature] = asyncio.create_task(
                self._timed_flush(signature)
            )
        return await fut

    def _spawn_flush(self, signature: Hashable) -> None:
        # pop the group SYNCHRONOUSLY (same event-loop tick as the
        # size check): if the pop waited for the spawned task's first
        # run, a burst of submits in one tick would all see a full
        # group and batch_fn would receive more than max_batch
        group = self._groups.pop(signature, [])
        if not group:
            return
        task = spawn_supervised(
            self._run_batch(signature, group),
            name=f"batcher-flush-{signature!r}",
        )
        self._inflight_flushes.add(task)
        task.add_done_callback(self._inflight_flushes.discard)

    async def _timed_flush(self, signature: Hashable) -> None:
        try:
            await asyncio.sleep(self.max_wait_ms / 1000.0)
            # Deregister BEFORE the (awaitable) flush: a request arriving
            # for this signature while batch_fn runs must see no timer
            # and schedule its own, or it would wait forever. The flush
            # itself runs detached for the same reason as in submit —
            # close() cancelling this timer must not kill a mid-flight
            # batch_fn.
            self._flush_tasks.pop(signature, None)
            self._spawn_flush(signature)
        except asyncio.CancelledError:
            self._flush_tasks.pop(signature, None)
            raise

    def _cancel_timer(self, signature: Hashable) -> None:
        task = self._flush_tasks.pop(signature, None)
        if task:
            task.cancel()

    async def _flush(self, signature: Hashable) -> None:
        group = self._groups.pop(signature, [])
        if not group:
            return
        await self._run_batch(signature, group)

    async def _run_batch(
        self, signature: Hashable, group: list[PendingRequest]
    ) -> None:
        self._stats["batches"] += 1
        self._stats["batched_requests"] += len(group)
        self._occupancy_samples.append(len(group))
        now = time.monotonic()
        now_wall = time.time()
        self._wait_samples.extend(now - r.enqueued_at for r in group)
        for r in group:
            if r.trace_ctx is not None:
                wait = now - r.enqueued_at
                # parent = the submitter's enclosing span, started_at
                # back-dated to the enqueue — the span sorts where the
                # wait actually happened in the tree
                tracing.record_span(
                    "batch.queue",
                    wait,
                    started_at=now_wall - wait,
                    parent_id=r.parent_span,
                    ctx=r.trace_ctx,
                    batch_size=len(group),
                )
        try:
            results = await self.batch_fn(
                signature, [r.payload for r in group]
            )
            if len(results) != len(group):
                raise RuntimeError(
                    f"batch_fn returned {len(results)} results for "
                    f"{len(group)} requests"
                )
            for req, res in zip(group, results):
                if not req.future.done():
                    req.future.set_result(res)
        except Exception as e:
            for req in group:
                if not req.future.done():
                    req.future.set_exception(e)

    async def close(self) -> None:
        self._closed = True
        for signature in list(self._groups):
            self._cancel_timer(signature)
            await self._flush(signature)
        # drain flushes already in flight — close() is a real barrier,
        # not a fire-and-forget (results land before shutdown proceeds)
        while self._inflight_flushes:
            await asyncio.gather(
                *list(self._inflight_flushes), return_exceptions=True
            )

    @property
    def stats(self) -> dict:
        s = dict(self._stats)
        s["avg_batch_size"] = (
            s["batched_requests"] / s["batches"] if s["batches"] else 0.0
        )
        # how long requests sat in the queue before their group flushed
        # (from PendingRequest.enqueued_at) — the latency cost of
        # batching, observable next to the throughput win
        waits = sorted(self._wait_samples)
        if waits:
            s["queue_wait_ms"] = {
                "p50": round(1000 * waits[len(waits) // 2], 3),
                "p95": round(1000 * waits[min(int(len(waits) * 0.95), len(waits) - 1)], 3),
                "samples": len(waits),
            }
        else:
            s["queue_wait_ms"] = {"p50": 0.0, "p95": 0.0, "samples": 0}
        occ = sorted(self._occupancy_samples)
        if occ:
            s["occupancy"] = {
                "p50": occ[len(occ) // 2],
                "p95": occ[min(int(len(occ) * 0.95), len(occ) - 1)],
                "mean": round(sum(occ) / len(occ), 3),
                "samples": len(occ),
            }
        else:
            s["occupancy"] = {"p50": 0, "p95": 0, "mean": 0.0, "samples": 0}
        return s
