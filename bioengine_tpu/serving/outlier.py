"""Gray-failure detection — per-replica latency outliers and probation.

The PR 4 breaker and failover only see **fail-stop** failures: a
replica must raise before any defense engages. A **gray-failing**
replica — still passing health checks, still answering, but at 10× the
latency of its siblings (thermal throttling, a wedged neighbor VM, a
dying disk behind the page cache) — is invisible to all of them, and at
production scale it dominates tail latency.

This module closes that gap with latency evidence the request path
already produces: every successful attempt's service time feeds a
per-replica EWMA, compared against the **deployment median** (the
lower median — with two replicas, the plain median averages the
outlier in and can never exceed ratio 2). A replica whose EWMA stays
above ``ratio × median`` for longer than ``excursion_s`` enters
**PROBATION**: soft-ejected from the scored pick like a breaker trip,
but — exactly like the scheduler's infeasible-probe pattern — still
probed with a trickle of real traffic (every ``probe_every``-th pick)
so recovery is observed, not assumed: when the probed EWMA falls back
under ``recovery_ratio × median``, the replica returns to HEALTHY on
its own.

The median comparison is also the adversarial-case guard: when the
WHOLE deployment slows down together (recompile, bigger batches, input
shift), every EWMA rises, the median rises with them, no ratio moves —
and nobody gets ejected. Probation is only ever a minority verdict
(``max_eject_fraction``), so a correlated excursion can never empty
the routing set.

The tracker also keeps a bounded reservoir of recent deployment-wide
service times; its p95 is what derives the request-hedging delay
(``DeploymentHandle`` launches a second attempt when the first is
slower than most requests ever are — see controller.py).

Knobs (read once at config construction):

=================================  ======= ==============================
``BIOENGINE_OUTLIER``              1       0 disables detection entirely
``BIOENGINE_OUTLIER_RATIO``        3.0     excursion threshold vs median
``BIOENGINE_OUTLIER_EXCURSION_S``  10.0    persistence before probation
``BIOENGINE_OUTLIER_PROBE_EVERY``  8       trickle: every Nth pick probes
``BIOENGINE_HEDGE_DELAY_MS``       0       fixed hedge delay (0 = p95)
=================================  ======= ==============================
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from bioengine_tpu.utils import flight, metrics

REPLICA_PROBATIONS = metrics.counter(
    "replica_probations_total",
    "replicas soft-ejected as latency outliers (gray-failure defense)",
    ("app", "deployment"),
)

# floor under the derived hedge delay: hedging below a few ms just
# doubles load on an uncontended deployment without helping the tail
_HEDGE_FLOOR_S = 0.002
# before the reservoir has this many samples, the p95 is noise — use
# the default delay instead
_MIN_HEDGE_SAMPLES = 20
_DEFAULT_HEDGE_DELAY_S = 0.05


@dataclass(frozen=True)
class OutlierConfig:
    """Gray-failure detection knobs. One instance per controller,
    env-derived by default (read once — this sits on the hot path)."""

    enabled: bool = True
    ewma_alpha: float = 0.3
    ratio: float = 3.0               # EWMA vs deployment median → outlier
    recovery_ratio: float = 1.5      # EWMA back under this → recover
    excursion_s: float = 10.0        # persistence before probation
    min_samples: int = 8             # per-replica samples before eligible
    probe_every: int = 8             # trickle: every Nth pick probes
    max_eject_fraction: float = 0.5  # probation is a minority verdict
    min_latency_s: float = 0.001     # ignore sub-ms noise medians
    hedge_delay_s: float = 0.0       # fixed hedge delay; 0 = p95-derived
    # consecutive hedge losses (hedge launched after the p95 delay AND
    # a sibling finished first) before probation — the detection path
    # that still works when hedging itself has dried up the EWMA's
    # sample stream (losers are cancelled, not measured)
    hedge_streak_limit: int = 5

    @classmethod
    def from_env(cls) -> "OutlierConfig":
        env = os.environ.get
        return cls(
            enabled=env("BIOENGINE_OUTLIER", "1") not in ("0", "false", ""),
            ratio=float(env("BIOENGINE_OUTLIER_RATIO", "3.0")),
            excursion_s=float(env("BIOENGINE_OUTLIER_EXCURSION_S", "10.0")),
            probe_every=int(env("BIOENGINE_OUTLIER_PROBE_EVERY", "8")),
            hedge_delay_s=float(env("BIOENGINE_HEDGE_DELAY_MS", "0")) / 1000.0,
        )


@dataclass
class _ReplicaStats:
    ewma: Optional[float] = None
    samples: int = 0
    excursion_since: Optional[float] = None
    in_probation: bool = False
    hedge_streak: int = 0
    # probe completions measured since this probation began: exit needs
    # FRESH evidence — the EWMA frozen at entry time (hedging had dried
    # up the sample stream) must not exit the replica by itself
    samples_in_probation: int = 0


@dataclass
class DeploymentLatencyTracker:
    """Per-deployment latency bookkeeping: one EWMA per replica, a
    deployment-wide p95 reservoir, probation verdicts, and the probe
    ticket counter. Owned by the controller (one per (app, deployment)
    key, swept at undeploy like every other router-state dict)."""

    app_id: str
    deployment: str
    cfg: OutlierConfig
    replicas: dict[str, _ReplicaStats] = field(default_factory=dict)
    recent: deque = field(default_factory=lambda: deque(maxlen=256))
    _probe_tick: int = 0
    _hedge_cache: tuple[float, float] = (0.0, 0.0)  # (computed_at, value)

    # ---- observation ------------------------------------------------------

    def note(
        self, replica_id: str, seconds: float, now: Optional[float] = None
    ) -> list[tuple[str, str]]:
        """Record one successful attempt's service time and return the
        probation transitions it caused as ``[(replica_id, "enter" |
        "exit"), ...]``. Cancelled hedge losers and failed attempts
        must NOT be noted — a cancelled attempt's wall time measures
        the winner, and a failure's measures the transport, not the
        replica's service rate.

        EVERY replica is re-evaluated on every note, not just the
        sampled one: once hedging starts rescuing requests off a gray
        replica, its own sample stream dries up (losers are cancelled,
        not measured) and its EWMA freezes at the elevated value — the
        excursion clock and the deployment median must keep moving on
        the siblings' samples or detection would stall exactly when
        the defense engages."""
        now = time.monotonic() if now is None else now
        st = self.replicas.setdefault(replica_id, _ReplicaStats())
        st.samples += 1
        st.hedge_streak = 0  # a measured completion breaks the streak
        if st.in_probation:
            st.samples_in_probation += 1
        if st.ewma is None:
            st.ewma = seconds
        else:
            a = self.cfg.ewma_alpha
            st.ewma = a * seconds + (1.0 - a) * st.ewma
        if not st.in_probation:
            # the hedge-delay reservoir tracks the HEALTHY serving set:
            # probe completions against a gray replica are exactly the
            # slow samples that would drag the p95 up and soften the
            # very hedges steering around it
            self.recent.append(seconds)
        if not self.cfg.enabled:
            return []
        return self.evaluate_all(now)

    def note_hedge_loss(
        self, replica_id: str, now: Optional[float] = None
    ) -> list[tuple[str, str]]:
        """A hedge launched against this replica and WON. Not failure
        evidence and not a latency sample (the loser was cancelled —
        the satellite contract), but a sustained streak of them is an
        honest *relative* signal: each one means this replica ran past
        the deployment p95 while a sibling finished the same call
        first. Past ``hedge_streak_limit`` consecutive losses the
        replica enters probation even though its EWMA froze when
        hedging dried up its sample stream."""
        if not self.cfg.enabled:
            return []
        now = time.monotonic() if now is None else now
        st = self.replicas.setdefault(replica_id, _ReplicaStats())
        st.hedge_streak += 1
        transitions: list[tuple[str, str]] = []
        if (
            not st.in_probation
            and st.hedge_streak >= self.cfg.hedge_streak_limit
            and self._median() is not None
            and self._minority_ok()
        ):
            st.in_probation = True
            st.excursion_since = None
            st.samples_in_probation = 0
            transitions.append((replica_id, "enter"))
        for t in self.evaluate_all(now):
            if t not in transitions:
                transitions.append(t)
        return transitions

    def evaluate_all(self, now: Optional[float] = None) -> list[tuple[str, str]]:
        now = time.monotonic() if now is None else now
        # ONE median pass per evaluation, shared by every replica's
        # verdict — this runs on the request hot path, and a per-replica
        # re-sort would be O(R^2 log R) per noted request
        median = self._median()
        out = []
        for rid, st in self.replicas.items():
            transition = self._evaluate(rid, st, now, median)
            if transition is not None:
                out.append((rid, transition))
        return out

    def forget(self, replica_id: str) -> None:
        """A restarted/retired replica's samples must not haunt its
        successor (ids are fresh per start; every replica-death path —
        retire, health-loop restart, undeploy sweep — calls this)."""
        self.replicas.pop(replica_id, None)

    # ---- verdicts ---------------------------------------------------------

    def _median(self) -> Optional[float]:
        """LOWER median of the per-replica EWMAs (matured replicas
        only). ``median_low`` and not the mean-of-middle-two: with two
        replicas the plain median averages the outlier in, capping the
        observable ratio at 2 and blinding the detector exactly where
        gray failure hurts most (small deployments)."""
        vals = sorted(
            st.ewma
            for st in self.replicas.values()
            if st.ewma is not None and st.samples >= self.cfg.min_samples
        )
        if not vals:
            return None
        return vals[(len(vals) - 1) // 2]

    def _evaluate(
        self,
        replica_id: str,
        st: _ReplicaStats,
        now: float,
        median: Optional[float],
    ) -> Optional[str]:
        if median is None or st.samples < self.cfg.min_samples:
            return None
        floor = max(median, self.cfg.min_latency_s)
        if st.in_probation:
            if (
                st.samples_in_probation >= 2
                and st.ewma <= self.cfg.recovery_ratio * floor
            ):
                st.in_probation = False
                st.excursion_since = None
                st.samples_in_probation = 0
                return "exit"
            return None
        if st.ewma > self.cfg.ratio * floor:
            if st.excursion_since is None:
                st.excursion_since = now
                return None
            if now - st.excursion_since < self.cfg.excursion_s:
                return None
            # the excursion persisted — but probation stays a MINORITY
            # verdict: when half the deployment looks like an outlier,
            # the baseline is what moved, not the replicas
            if not self._minority_ok():
                return None
            st.in_probation = True
            st.samples_in_probation = 0
            return "enter"
        st.excursion_since = None
        return None

    def _minority_ok(self) -> bool:
        already = sum(1 for s in self.replicas.values() if s.in_probation)
        return (already + 1) <= self.cfg.max_eject_fraction * max(
            1, len(self.replicas)
        )

    def ewma(self, replica_id: str) -> Optional[float]:
        st = self.replicas.get(replica_id)
        return None if st is None else st.ewma

    def sample_count(self, replica_id: str) -> int:
        st = self.replicas.get(replica_id)
        return 0 if st is None else st.samples

    # ---- probe trickle ----------------------------------------------------

    def take_probe_ticket(self) -> bool:
        """True every ``probe_every``-th call — the pick that routes to
        a probation replica so its recovery can be observed with real
        traffic (the self-correcting half of soft ejection)."""
        self._probe_tick += 1
        return self._probe_tick % max(1, self.cfg.probe_every) == 0

    # ---- hedge delay ------------------------------------------------------

    def hedge_delay_s(self, now: Optional[float] = None) -> float:
        """The request-hedging trigger delay: deployment-wide p95 of
        recent service times (a fixed ``BIOENGINE_HEDGE_DELAY_MS``
        overrides). Cached for 1 s — sorting 256 floats per request
        would be an odd way to spend the fast path."""
        if self.cfg.hedge_delay_s > 0:
            return self.cfg.hedge_delay_s
        if len(self.recent) < _MIN_HEDGE_SAMPLES:
            return _DEFAULT_HEDGE_DELAY_S
        now = time.monotonic() if now is None else now
        computed_at, value = self._hedge_cache
        if value > 0.0 and now - computed_at < 1.0:
            return value
        s = sorted(self.recent)
        p95 = s[min(int(len(s) * 0.95), len(s) - 1)]
        value = max(_HEDGE_FLOOR_S, p95)
        self._hedge_cache = (now, value)
        return value

    # ---- status -----------------------------------------------------------

    def describe(self) -> dict:
        return {
            "enabled": self.cfg.enabled,
            "median_ewma_s": self._median(),
            "hedge_delay_s": round(self.hedge_delay_s(), 6),
            "replicas": {
                rid: {
                    "ewma_s": None if st.ewma is None else round(st.ewma, 6),
                    "samples": st.samples,
                    "in_probation": st.in_probation,
                    "hedge_streak": st.hedge_streak,
                }
                for rid, st in self.replicas.items()
            },
        }


def record_probation_event(
    app_id: str, deployment: str, replica_id: str, phase: str, **attrs
) -> None:
    """One flight event per probation transition — the incident-ring
    evidence `bioengine debug bundle` and the runbook read."""
    flight.record(
        "replica.probation",
        severity="warning" if phase == "enter" else "info",
        app=app_id,
        deployment=deployment,
        replica=replica_id,
        phase=phase,
        **attrs,
    )
