"""Global request scheduler — cross-replica continuous batching,
admission control with priority classes, predictive autoscaling.

The per-request router (``ServeController._pick_replica``) answers
"which replica takes THIS call"; at scale the controller must answer a
different question — "what should the fleet execute next" — and that is
a scheduling problem. This module adds, per deployment:

- **Cross-replica continuous batching.** Compatible requests (same
  batch signature — method + non-payload argument values + payload
  shape bucket, the controller-side analog of the replica batcher's
  model+bucket+mesh key) coalesce into one :class:`_Group` dispatched
  to a single replica as ONE ``call_batch`` round trip. On the replica
  the K members execute in the same event-loop window, so a deployment
  with its own ``ContinuousBatcher`` merges them into one dp-sharded
  forward instead of K separate forwards spread thin over the fleet.
- **Admission control.** Priority classes (``interactive`` > ``bulk``
  > ``background``) scheduled by deficit-weighted round robin; a
  per-deployment queue-depth budget and optional per-tenant quota shed
  load with a typed :class:`AdmissionRejectedError` instead of letting
  queues grow unbounded; requests are ordered earliest-deadline-first
  within a class and are failed fast (``DeadlineExceeded``) the moment
  they could no longer finish in time — a request never waits past the
  point where waiting can help.
- **A pluggable cost model.** Replica choice is a scored decision over
  load/breaker/affinity features (:class:`HeuristicCostModel` by
  default). GDP/Placeto (PAPERS.md) show learned placement beating
  fixed heuristics — a learned policy drops in by assigning
  ``ServeController.scorer_factory`` (the feature dict is the contract,
  not this scorer's arithmetic).
- **Predictive autoscaling.** :class:`LoadPredictor` keeps EWMAs of
  arrival rate and per-request service time; the controller's autoscale
  pass (and a cheap submit-time early trigger that wakes the health
  loop) scales up when utilization or projected queue wait crosses the
  threshold — BEFORE queues saturate, not after — and scales down only
  after ``scale_down_ticks`` consecutive idle verdicts (hysteresis), so
  a traffic dip never thrashes replicas that are expensive to rebuild.

Scheduling is opt-in per deployment (``DeploymentSpec.scheduling`` /
the manifest's ``deployment_config.<dep>.scheduling``); deployments
without it keep the per-request router path byte-for-byte.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Hashable, Optional, Protocol

from bioengine_tpu.rpc.protocol import RemoteError
from bioengine_tpu.serving.errors import (
    AdmissionRejectedError,
    DeadlineExceeded,
    NoHealthyReplicasError,
    ReplicaUnavailableError,
    is_caller_timeout,
    is_retryable,
)
from bioengine_tpu.serving.replica import (
    DEFAULT_DRAIN_TIMEOUT_S,
    ROUTABLE_STATES,
    ReplicaState,
)
from bioengine_tpu.utils import flight, metrics, tracing
from bioengine_tpu.utils.tasks import spawn_supervised

# Every Nth consecutive deadline_infeasible verdict is admitted as a
# PROBE instead of shed: a poisoned or stale service estimate (e.g. one
# 120 s cold-compile outlier seeding the EWMA) would otherwise shed ALL
# deadlined traffic forever — rejected requests never complete, so
# nothing could ever correct the estimate. A completed probe re-grounds
# it; probes skip the predictive shed but still fail on true expiry.
INFEASIBLE_PROBE_EVERY = 8

# fixed class order IS the tie-break: when several classes hold credit,
# the most latency-sensitive one goes first
DEFAULT_CLASS_WEIGHTS: dict[str, float] = {
    "interactive": 8.0,   # user-facing inference
    "bulk": 2.0,          # bulk embedding / batch jobs
    "background": 1.0,    # fine-tune / maintenance traffic
}

SCHED_ADMITTED = metrics.counter(
    "scheduler_admitted_total",
    "requests admitted into a deployment scheduler queue",
    ("app", "deployment", "priority"),
)
SCHED_REJECTED = metrics.counter(
    "scheduler_rejected_total",
    "requests shed by admission control",
    ("app", "deployment", "reason"),
)
SCHED_QUEUE_WAIT = metrics.histogram(
    "scheduler_queue_wait_seconds",
    "time a request waited in the scheduler before dispatch",
    ("app", "deployment", "priority"),
)
SCHED_BATCH_SIZE = metrics.histogram(
    "scheduler_batch_size",
    "requests per dispatched cross-replica group",
    ("app", "deployment"),
    buckets=metrics.BATCH_SIZE_BUCKETS,
)
SCHED_DISPATCHES = metrics.counter(
    "scheduler_dispatches_total",
    "groups dispatched to a replica (one call_batch round trip each)",
    ("app", "deployment"),
)


def _collect_schedulers(instances: list) -> list:
    """Scrape-time scheduler gauges: per-class queue depth and the
    predictor's projection — the live inputs of admission and the
    predictive autoscaler, visible on the same /metrics plane that
    shows their consequences."""
    out: list[metrics.Sample] = []
    for s in instances:
        if s._closed:
            continue
        labels = {"app": s.app_id, "deployment": s.deployment}
        for cls, q in s._queues.items():
            out.append(
                metrics.Sample(
                    "scheduler_queue_depth",
                    len(q),
                    {**labels, "priority": cls},
                    help="requests waiting in a scheduler class queue",
                )
            )
        proj = s.predictor.projection(
            time.monotonic(), s.waiting, max(1, s._n_routable())
        )
        out.append(
            metrics.Sample(
                "scheduler_projected_wait_seconds",
                round(proj["projected_wait_s"], 6),
                labels,
                help="predicted queue wait at current arrival/service rates",
            )
        )
        out.append(
            metrics.Sample(
                "scheduler_inflight_groups",
                len(s._inflight),
                labels,
                help="dispatched groups currently executing",
            )
        )
    return out


_SCHEDULERS = metrics.InstanceSet("deployment_scheduler", _collect_schedulers)


# ---------------------------------------------------------------------------
# batch-compatibility signature
# ---------------------------------------------------------------------------


def _sig_value(v: Any) -> Hashable:
    """One argument's contribution to the compatibility key. Scalars
    and strings contribute their VALUE (model ids, format flags — a
    different model must never co-batch); array-likes contribute their
    per-item shape + dtype (the bucket — the batch dim is exactly what
    coalescing merges, so it is excluded); everything else contributes
    only its type (opaque payloads are conservatively incompatible only
    when their types differ — matching the replica-side batcher, which
    re-checks its own model+bucket+mesh signature anyway)."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    shape = getattr(v, "shape", None)
    if shape is not None:
        item_shape = tuple(shape[1:]) if len(shape) > 1 else tuple(shape)
        return ("nd", item_shape, str(getattr(v, "dtype", "")))
    if isinstance(v, (list, tuple)):
        return (type(v).__name__, len(v))
    if isinstance(v, dict):
        return ("dict", tuple(sorted(str(k) for k in v)))
    return type(v).__name__


# sorted-kwargs-key memo: the signature is rebuilt on EVERY submit, and
# re-sorting the same handful of kwarg-key tuples re-serializes scalar
# kwargs for no reason (the --hot-path-report offender PR 16 mapped).
# Keyed by the kwargs keys IN INSERTION ORDER — handles call with a
# stable shape, so this hits ~always. Bounded; eviction is arbitrary.
_SORTED_KEYS_CACHE: dict[tuple, tuple] = {}
_SORTED_KEYS_CACHE_MAX = 512


def batch_signature(method: str, args: tuple, kwargs: dict) -> Hashable:
    """Controller-side compatibility key: requests sharing a signature
    may ride one dispatched group (the same replica, one round trip)."""
    if kwargs:
        keys = tuple(kwargs)
        skeys = _SORTED_KEYS_CACHE.get(keys)
        if skeys is None:
            if len(_SORTED_KEYS_CACHE) >= _SORTED_KEYS_CACHE_MAX:
                _SORTED_KEYS_CACHE.pop(next(iter(_SORTED_KEYS_CACHE)))
            skeys = _SORTED_KEYS_CACHE[keys] = tuple(sorted(keys))
        kw_sig = tuple((k, _sig_value(kwargs[k])) for k in skeys)
    else:
        kw_sig = ()
    return (method, tuple(_sig_value(a) for a in args), kw_sig)


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclass
class SchedulingConfig:
    """Per-deployment scheduler knobs (manifest:
    ``deployment_config.<dep>.scheduling``)."""

    enabled: bool = True
    max_batch: int = 8                 # group size cap per dispatch
    max_wait_ms: float = 5.0           # group coalescing window
    max_queue_depth: int = 256         # admission budget (all classes)
    class_weights: dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_CLASS_WEIGHTS)
    )
    default_class: str = "interactive"
    tenant_quota: Optional[int] = None  # max waiting requests per tenant
    target_wait_s: float = 1.0          # predictive scale-up threshold
    scale_down_ticks: int = 3           # hysteresis before scale-down
    ewma_alpha: float = 0.2
    # consume the SLO engine's error-budget burn as an autoscale
    # up-pressure signal (needs a manifest slo: block; off by default —
    # the loop only closes where an operator asked it to)
    slo_pressure: bool = False

    @classmethod
    def from_config(cls, cfg: dict) -> "SchedulingConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(
                f"unknown scheduling config keys: {unknown} "
                f"(accepted: {sorted(known)})"
            )
        out = cls()
        if "enabled" in cfg:
            out.enabled = bool(cfg["enabled"])
        if "max_batch" in cfg:
            out.max_batch = max(1, int(cfg["max_batch"]))
        if "max_wait_ms" in cfg:
            out.max_wait_ms = float(cfg["max_wait_ms"])
        if "max_queue_depth" in cfg:
            out.max_queue_depth = max(1, int(cfg["max_queue_depth"]))
        if "class_weights" in cfg:
            weights = {
                str(k): float(v) for k, v in dict(cfg["class_weights"]).items()
            }
            if not weights or min(weights.values()) <= 0:
                raise ValueError("class_weights must be positive")
            out.class_weights = weights
        if "default_class" in cfg:
            out.default_class = str(cfg["default_class"])
        if out.default_class not in out.class_weights:
            raise ValueError(
                f"default_class '{out.default_class}' not in class_weights "
                f"{sorted(out.class_weights)}"
            )
        if "tenant_quota" in cfg and cfg["tenant_quota"] is not None:
            out.tenant_quota = max(1, int(cfg["tenant_quota"]))
        if "target_wait_s" in cfg:
            out.target_wait_s = float(cfg["target_wait_s"])
        if "scale_down_ticks" in cfg:
            out.scale_down_ticks = max(1, int(cfg["scale_down_ticks"]))
        if "ewma_alpha" in cfg:
            out.ewma_alpha = min(1.0, max(0.01, float(cfg["ewma_alpha"])))
        if "slo_pressure" in cfg:
            out.slo_pressure = bool(cfg["slo_pressure"])
        return out


# ---------------------------------------------------------------------------
# cost-model scorer (pluggable — the learnable policy surface)
# ---------------------------------------------------------------------------


class ReplicaScorer(Protocol):
    """Placement policy contract: lower score wins. ``features`` is the
    stable interface a learned policy consumes — keys: ``load``,
    ``queued``, ``max_ongoing``, ``breaker_failures``,
    ``signature_affinity``, ``avoided``, ``probation``, ``group_size``.

    The dict is a REUSED template mutated between ``score`` calls (one
    allocation per scheduler, not per candidate): read synchronously,
    copy (``dict(features)``) before retaining for training datasets or
    deferred scoring."""

    def score(self, features: dict) -> float: ...


class HeuristicCostModel:
    """Default scorer: occupancy plus a breaker-risk penalty, minus a
    warm-program affinity bonus (the replica that last served this
    signature holds the compiled program and batcher group hot).
    Replicas the request already failed on score worst — preferred
    against, but still usable as a last resort, matching the router."""

    def __init__(
        self,
        queued_weight: float = 0.1,
        breaker_penalty: float = 0.5,
        affinity_bonus: float = 0.15,
        avoid_penalty: float = 10.0,
        probation_penalty: float = 20.0,
    ):
        self.queued_weight = queued_weight
        self.breaker_penalty = breaker_penalty
        self.affinity_bonus = affinity_bonus
        self.avoid_penalty = avoid_penalty
        self.probation_penalty = probation_penalty

    def score(self, features: dict) -> float:
        s = float(features.get("load", 0.0))
        s += self.queued_weight * float(features.get("queued", 0) or 0)
        s += self.breaker_penalty * float(
            features.get("breaker_failures", 0) or 0
        )
        if features.get("signature_affinity"):
            s -= self.affinity_bonus
        if features.get("avoided"):
            s += self.avoid_penalty
        if features.get("probation"):
            # latency outlier (gray failure): soft ejection — scored
            # far behind every healthy sibling, above only nothing at
            # all (the trickle probe bypasses scoring entirely)
            s += self.probation_penalty
        return s


# ---------------------------------------------------------------------------
# load prediction (EWMA arrival rate + service time)
# ---------------------------------------------------------------------------


class LoadPredictor:
    """EWMA of arrival rate and per-request service time; the scaling
    signal is computed from MEASURED flow, not from already-saturated
    queues — projected wait crosses the threshold while the queue is
    still shallow, which is the whole point of scaling predictively."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.arrival_rate = 0.0        # requests/s (EWMA)
        self.service_s = 0.0           # seconds/request (EWMA)
        self._last_arrival: Optional[float] = None
        self._below_ticks = 0

    def note_arrival(self, now: float) -> None:
        if self._last_arrival is not None:
            dt = max(now - self._last_arrival, 1e-4)
            inst = 1.0 / dt
            self.arrival_rate += self.alpha * (inst - self.arrival_rate)
        self._last_arrival = now

    def note_service(
        self, n_requests: int, wall_s: float, reground: bool = False
    ) -> None:
        per = wall_s / max(1, n_requests)
        if self.service_s == 0.0 or reground:
            # reground: the sample comes from an infeasibility PROBE —
            # it exists precisely because the current estimate is
            # suspect (poisoned by an outlier, or stale), so it
            # replaces the estimate instead of nudging an EWMA that
            # would take dozens of samples to climb down from a 120 s
            # cold-compile spike
            self.service_s = per
        else:
            self.service_s += self.alpha * (per - self.service_s)

    def service_estimate_s(self) -> float:
        return self.service_s

    def current_rate(self, now: float) -> float:
        """The EWMA, capped by the observed idle gap — an EWMA only
        updates on arrival, so without the cap a traffic stop would
        freeze a high rate forever and block scale-down."""
        if self._last_arrival is None:
            return 0.0
        gap = max(now - self._last_arrival, 1e-4)
        return min(self.arrival_rate, 1.0 / gap)

    def projection(self, now: float, queue_depth: int, n_replicas: int) -> dict:
        """Replicas modeled as serial servers (honest for accelerator
        work — concurrent calls time-share the same chips): capacity is
        n/s requests/s, utilization is (arrival rate)/(capacity), and
        the projected wait of a NEW arrival is the backlog divided by
        drain rate."""
        n = max(1, n_replicas)
        s = self.service_s
        rate = self.current_rate(now)
        utilization = rate * s / n
        projected_wait = (queue_depth * s / n) if s > 0 else 0.0
        return {
            "arrival_rate": round(rate, 3),
            "service_s": round(s, 6),
            "utilization": round(utilization, 4),
            "projected_wait_s": projected_wait,
            "queue_depth": queue_depth,
        }

    def decide(
        self,
        now: float,
        queue_depth: int,
        n_replicas: int,
        target_wait_s: float,
        target_load: float,
        scale_down_ticks: int,
    ) -> tuple[str, dict]:
        proj = self.projection(now, queue_depth, n_replicas)
        if (
            proj["utilization"] > target_load
            or proj["projected_wait_s"] > target_wait_s
        ):
            self._below_ticks = 0
            return "up", proj
        if proj["utilization"] < target_load / 2 and queue_depth == 0:
            # scale-down needs HYSTERESIS: one idle tick is noise, K
            # consecutive ones are a trend worth paying a drain for
            self._below_ticks += 1
            if self._below_ticks >= scale_down_ticks:
                self._below_ticks = 0
                return "down", proj
        else:
            self._below_ticks = 0
        return "hold", proj


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


@dataclass
class _Request:
    method: str
    args: tuple
    kwargs: dict
    signature: Hashable
    priority: str
    tenant: Optional[str]
    deadline: Optional[float]          # monotonic; None = unbounded
    timeout_s: Optional[float]         # per-attempt budget from the handle
    avoid: frozenset
    future: asyncio.Future
    # admitted despite an infeasible-looking deadline to re-ground the
    # service estimate — exempt from the predictive shed (absolute
    # expiry still applies)
    probe: bool = False
    # waiting-bookkeeping consumed exactly once (dispatch, shed, close,
    # or caller abandonment) — see _finish_waiting
    finished_waiting: bool = False
    enqueued_at: float = field(default_factory=time.monotonic)
    # sampled-trace identity captured at submit (None when unsampled):
    # queue wait is only measurable at dispatch, so the span is recorded
    # retroactively against the submitter's trace
    trace_ctx: Any = None
    parent_span: Optional[str] = None

    def sort_key(self) -> tuple:
        # EDF within a class; deadline-free requests keep arrival order
        # behind every deadlined one
        return (
            self.deadline if self.deadline is not None else float("inf"),
            self.enqueued_at,
        )

    def slack(self, now: float) -> float:
        return (
            float("inf") if self.deadline is None else self.deadline - now
        )


class DeploymentScheduler:
    """One per scheduled deployment, owned by the controller. The
    handle's retry envelope stays in charge of failover/backoff —
    ``submit`` is one attempt: admission, fair queueing, group
    coalescing, scored dispatch, result delivery."""

    def __init__(
        self,
        controller,
        app_id: str,
        deployment: str,
        spec,
        config: SchedulingConfig,
        scorer: Optional[ReplicaScorer] = None,
    ):
        self.controller = controller
        self.app_id = app_id
        self.deployment = deployment
        self.spec = spec
        self.cfg = config
        self.scorer: ReplicaScorer = scorer or HeuristicCostModel()
        self._queues: dict[str, list[_Request]] = {
            c: [] for c in config.class_weights
        }
        self._deficit: dict[str, float] = {c: 0.0 for c in config.class_weights}
        self._open: dict[Hashable, list[_Request]] = {}
        self._timers: dict[Hashable, asyncio.Task] = {}
        self._timer_fire_at: dict[Hashable, float] = {}
        self._inflight: set[asyncio.Task] = set()
        self._waiting_by_tenant: dict[str, int] = {}
        self.waiting = 0               # class queues + open groups
        self._fast_inflight = 0        # uncontended inline dispatches
        self._closed = False
        self._last_scale_signal = 0.0
        self.predictor = LoadPredictor(alpha=config.ewma_alpha)
        self._last_signature: dict[str, Hashable] = {}  # replica -> sig
        # cheap in-process counters for tests/describe (metric children
        # are the exported truth; this dict avoids label lookups there)
        self.stats = {
            "admitted": 0,
            "rejected": 0,
            "dispatched_groups": 0,
            "dispatched_requests": 0,
            "shed_deadline": 0,
            "fast_path": 0,
            "infeasible_probes": 0,
            "unknown_priority": 0,
        }
        self._infeasible_streak = 0
        self._warned_priorities: set = set()
        # SLO burn-rate pressure hook (the pluggable half of "close the
        # loop"): a zero-arg callable returning the deployment's current
        # burn normalized to the page threshold. None (the default)
        # keeps scaling purely queue-projection driven; the controller
        # wires it only when scheduling.slo_pressure is on AND the
        # deployment carries a manifest slo: block.
        self.pressure_fn: Optional[Callable[[], float]] = None
        self._m_admitted: dict[str, Any] = {}
        self._m_wait: dict[str, Any] = {}
        self._m_rejected: dict[str, Any] = {}  # reason -> counter child
        self._m_batch = SCHED_BATCH_SIZE.labels(app_id, deployment)
        self._m_dispatch = SCHED_DISPATCHES.labels(app_id, deployment)
        # reusable scorer feature dict — see _best_replica
        self._feat_template: dict[str, Any] = {
            "load": 0,
            "queued": 0,
            "max_ongoing": 0,
            "breaker_failures": 0,
            "signature_affinity": False,
            "avoided": False,
            "probation": False,
            "group_size": 1,
        }
        _SCHEDULERS.add(self)

    # ---- admission ----------------------------------------------------------

    async def submit(
        self,
        method: str,
        args: tuple,
        kwargs: dict,
        options,
        timeout_s: Optional[float] = None,
        deadline: Optional[float] = None,
        avoid: Optional[frozenset] = None,
    ) -> Any:
        if self._closed:
            raise ReplicaUnavailableError(
                f"scheduler for {self.app_id}/{self.deployment} is closed"
            )
        now = time.monotonic()
        priority = getattr(options, "priority", None) or self.cfg.default_class
        if priority not in self._queues:
            self._note_unknown_priority(priority)
            priority = self.cfg.default_class
        tenant = getattr(options, "tenant", None)
        if self.waiting >= self.cfg.max_queue_depth:
            self._reject("queue_full", priority, tenant, method)
        if (
            tenant is not None
            and self.cfg.tenant_quota is not None
            and self._waiting_by_tenant.get(tenant, 0) >= self.cfg.tenant_quota
        ):
            self._reject("tenant_quota", priority, tenant, method)
        est = self.predictor.service_estimate_s()
        probe = False
        if deadline is not None:
            if deadline - now < est:
                # admitting would only burn queue space: even an empty
                # fleet could not finish this before its deadline —
                # except every Nth in a row, which probes through so a
                # wrong estimate can never shed deadlined traffic
                # forever (see INFEASIBLE_PROBE_EVERY)
                self._infeasible_streak += 1
                if self._infeasible_streak % INFEASIBLE_PROBE_EVERY != 0:
                    self._reject(
                        "deadline_infeasible", priority, tenant, method
                    )
                probe = True
                self.stats["infeasible_probes"] += 1
            else:
                self._infeasible_streak = 0
        signature = batch_signature(method, tuple(args), kwargs)
        if (
            self.waiting == 0
            and not self._inflight
            and self._fast_inflight == 0
            and not self._open
        ):
            # UNCONTENDED fast path: a lone request on an idle
            # deployment gains nothing from queueing — no companion
            # exists to coalesce with, and charging it the batching
            # window would be pure latency. Dispatch inline through the
            # scored pick; the moment a second request overlaps, the
            # fair-queue path takes over and coalescing resumes.
            replica = self._pick_now(signature, avoid or frozenset())
            if replica is not None:
                return await self._fast_dispatch(
                    replica, signature, method, args, kwargs,
                    timeout_s, priority, now, probe,
                )
        self.predictor.note_arrival(now)
        ctx, span_id = tracing.current_trace_and_span()
        sampled = ctx is not None and ctx.sampled
        req = _Request(
            method=method,
            args=tuple(args),
            kwargs=dict(kwargs),
            signature=signature,
            priority=priority,
            tenant=tenant,
            deadline=deadline,
            timeout_s=timeout_s,
            avoid=avoid or frozenset(),
            probe=probe,
            future=asyncio.get_running_loop().create_future(),
            trace_ctx=ctx if sampled else None,
            parent_span=span_id if sampled else None,
        )
        queue = self._queues[priority]
        # EDF insertion: linear from the back (deadline-free traffic —
        # the common case — appends in O(1))
        idx = len(queue)
        key = req.sort_key()
        while idx > 0 and queue[idx - 1].sort_key() > key:
            idx -= 1
        queue.insert(idx, req)
        self.waiting += 1
        if tenant is not None:
            self._waiting_by_tenant[tenant] = (
                self._waiting_by_tenant.get(tenant, 0) + 1
            )
        self._note_admitted(priority)
        self._maybe_signal_scale(now)
        self._pump()
        try:
            if timeout_s is None:
                return await req.future
            # the member's OWN budget bounds its wait, whatever group
            # it lands in — co-batching with a no-timeout companion
            # must not let a tight-budget caller inherit the loosest
            # member's budget (the group's host-side abort still uses
            # the group max; this is the caller-side cut, exactly like
            # the router's call_bounded wrapper). wait_for cancels the
            # future, and _run_group skips done futures at delivery.
            return await asyncio.wait_for(req.future, timeout_s)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # the caller is GONE: the zombie stays in its queue/group
            # (delivery skips done futures) but must release its
            # admission depth now — live traffic must never be shed on
            # queue space held by futures nobody is waiting on
            self._finish_waiting(req)
            raise

    def _note_unknown_priority(self, priority: str) -> None:
        """A mistagged request silently riding the default class would
        degrade real interactive traffic at the default weight with no
        operator signal — warn ONCE per unknown tag (a busy mistagged
        client must not spam the log) and keep a counter + flight
        event. Manifest-side typos already fail the build; request-side
        ones can only be flagged at runtime."""
        self.stats["unknown_priority"] += 1
        if priority in self._warned_priorities:
            return
        self._warned_priorities.add(priority)
        self.controller.logger.warning(
            f"unknown request priority '{priority}' on "
            f"{self.app_id}/{self.deployment}; using "
            f"'{self.cfg.default_class}' (classes: {sorted(self._queues)})"
        )
        flight.record(
            "admission.unknown_priority",
            severity="warning",
            app=self.app_id,
            deployment=self.deployment,
            priority=str(priority)[:64],
            default=self.cfg.default_class,
        )

    def _note_admitted(self, priority: str) -> None:
        self.stats["admitted"] += 1
        if metrics.metrics_enabled():
            child = self._m_admitted.get(priority)
            if child is None:
                child = self._m_admitted[priority] = SCHED_ADMITTED.labels(
                    self.app_id, self.deployment, priority
                )
            child.inc()

    def _reject(
        self, reason: str, priority: str, tenant: Optional[str], method: str
    ) -> None:
        self.stats["rejected"] += 1
        child = self._m_rejected.get(reason)
        if child is None:
            child = self._m_rejected[reason] = SCHED_REJECTED.labels(
                self.app_id, self.deployment, reason
            )
        child.inc()
        flight.record(
            "admission.reject",
            severity="warning",
            app=self.app_id,
            deployment=self.deployment,
            method=method,
            reason=reason,
            priority=priority,
            tenant=tenant,
            queue_depth=self.waiting,
        )
        raise AdmissionRejectedError(
            f"{self.app_id}/{self.deployment}.{method} shed by admission "
            f"control ({reason}; depth={self.waiting}/"
            f"{self.cfg.max_queue_depth})",
            reason=reason,
        )

    def _best_replica(
        self, signature: Hashable, avoid: frozenset, group_size: int
    ):
        """ONE scored argmin over the routable replicas — the single
        place the scorer's feature contract is built, shared by the
        fast path and the group-dispatch pick so the two can never
        drift. None when no routable replica exists right now.

        PROBATION replicas (latency outliers) ride the same contract:
        the ``probation`` feature lets any scorer — heuristic or
        learned — price the soft ejection, and the trickle probe
        (every Nth pick, serving/outlier.py) bypasses scoring entirely
        so recovery keeps being measured with real traffic."""
        app = self.controller.apps.get(self.app_id)
        if app is None:
            return None
        candidates = [
            r
            for r in app.replicas.get(self.deployment, [])
            if r.state in ROUTABLE_STATES
        ]
        probation = [
            r for r in candidates if r.state == ReplicaState.PROBATION
        ]
        if probation and len(probation) < len(candidates):
            tracker = self.controller._outlier_tracker(
                self.app_id, self.deployment
            )
            if tracker.take_probe_ticket():
                pool = [
                    r for r in probation if r.replica_id not in avoid
                ] or probation
                return pool[tracker._probe_tick % len(pool)]
        best = None
        best_score = None
        # one reusable feature dict, mutated per candidate: the scorer
        # contract is read-synchronously-then-forget (HeuristicCostModel
        # and any FittedCostModel must copy if they retain — documented
        # on ReplicaScorer). Building an 8-key dict literal per
        # candidate per pick was a measurable slice of the uncontended
        # submit budget.
        feats = self._feat_template
        feats["group_size"] = group_size
        breaker_counts = self.controller._breaker_counts
        last_sig = self._last_signature
        score = self.scorer.score
        for r in candidates:
            rid = r.replica_id
            feats["load"] = r.load
            feats["queued"] = getattr(r, "_queued", 0)
            feats["max_ongoing"] = r.max_ongoing_requests
            feats["breaker_failures"] = breaker_counts.get(rid, 0)
            feats["signature_affinity"] = last_sig.get(rid) == signature
            feats["avoided"] = rid in avoid
            feats["probation"] = r.state == ReplicaState.PROBATION
            s = score(feats)
            if best_score is None or s < best_score:
                best, best_score = r, s
        return best

    def _pick_now(self, signature: Hashable, avoid: frozenset):
        """Synchronous scored pick for the fast path; None when no
        routable replica exists right now (the queued path then parks
        through the restart window like the router does)."""
        return self._best_replica(signature, avoid, 1)

    async def _fast_dispatch(
        self,
        replica,
        signature: Hashable,
        method: str,
        args: tuple,
        kwargs: dict,
        timeout_s: Optional[float],
        priority: str,
        now: float,
        probe: bool = False,
    ):
        self.predictor.note_arrival(now)
        self._note_admitted(priority)
        self.stats["fast_path"] += 1
        self._fast_inflight += 1
        t0 = time.monotonic()
        try:
            result = await replica.call_bounded(
                method, args, kwargs, timeout_s=timeout_s
            )
        except Exception as e:
            # same breaker discipline as the router and group paths:
            # only transport-classified failures are replica-health
            # evidence — an app error (bad client input) or the
            # caller's own budget expiring must never eject a replica
            if not is_caller_timeout(e) and is_retryable(e):
                self.controller._breaker_failure(replica, e)
            self._attach_replica(e, replica)
            raise
        else:
            self.controller._breaker_success(replica)
            # successful service time feeds the gray-failure outlier
            # EWMA — same evidence stream as the router path
            self.controller._note_attempt_latency(
                replica, time.monotonic() - t0
            )
            self._last_signature[replica.replica_id] = signature
            self._prune_affinity()
            self.predictor.note_service(
                1, time.monotonic() - t0, reground=probe
            )
            return result
        finally:
            self._fast_inflight -= 1
            self._pump()  # work may have queued behind this dispatch

    # ---- fair dequeue + group formation -------------------------------------

    def _n_routable(self) -> int:
        app = self.controller.apps.get(self.app_id)
        if app is None:
            return 0
        return sum(
            1
            for r in app.replicas.get(self.deployment, [])
            if r.state in ROUTABLE_STATES
        )

    def _dispatch_capacity(self) -> int:
        # enough in-flight groups to keep every replica busy plus one
        # forming behind it; the backlog beyond that stays in the FAIR
        # queues, where priority weights decide who goes next
        return max(1, 2 * self._n_routable())

    def _next_request(self) -> Optional[_Request]:
        """Deficit-weighted round robin across class queues: every pass
        grants each backlogged class its weight in credit; one request
        costs one credit. Served shares converge to the weight ratio
        under saturation, and any positive weight guarantees progress —
        the bulk class can be slowed, never starved."""
        nonempty = [c for c in self._queues if self._queues[c]]
        if not nonempty:
            return None
        for c in self._queues:
            if not self._queues[c]:
                # empty classes don't bank credit (no burst after idle)
                self._deficit[c] = 0.0
        while True:
            for c in nonempty:
                if self._queues[c] and self._deficit[c] >= 1.0:
                    self._deficit[c] -= 1.0
                    return self._queues[c].pop(0)
            for c in nonempty:
                self._deficit[c] += self.cfg.class_weights.get(c, 1.0)

    def _pump(self) -> None:
        """Drain class queues into signature groups while dispatch
        capacity remains. Full groups dispatch immediately; partial
        groups wait out the coalescing window (bounded by the tightest
        member's slack) for companions.

        Capacity gates the OPENING of new groups (open + in-flight
        stays within bound): forming a group commits its members past
        the fair queues, and a signature-diverse backlog would
        otherwise drain entirely into open groups in one pass — every
        timer-fired dispatch then runs regardless of load, and
        late-arriving interactive traffic would queue at replica
        semaphores instead of overtaking via class weights. JOINING an
        already-open group is always allowed — that's coalescing, the
        whole point — so a same-signature flood still fills groups to
        max_batch while the excess backlog stays in the fair queues,
        where DRR/EDF decide who goes next."""
        if self._closed:
            return
        while True:
            req = self._next_request()
            if req is None:
                return
            if (
                req.signature not in self._open
                and len(self._inflight) + len(self._open)
                >= self._dispatch_capacity()
            ):
                # no capacity for a NEW group: hand the request back to
                # the head of its class queue (it was the head — EDF
                # order is preserved) with its DRR credit refunded, and
                # stop pumping until a dispatch slot frees
                self._queues[req.priority].insert(0, req)
                self._deficit[req.priority] += 1.0
                return
            now = time.monotonic()
            if req.deadline is not None:
                # a probe exists to correct the estimate, so the
                # estimate must not be allowed to shed it — only true
                # expiry can
                est = (
                    0.0 if req.probe
                    else self.predictor.service_estimate_s()
                )
                if req.deadline - now <= est:
                    # the request can no longer finish — fail NOW, not
                    # after burning a replica slot on a doomed call
                    self._finish_waiting(req)
                    self.stats["shed_deadline"] += 1
                    if not req.future.done():
                        req.future.set_exception(
                            DeadlineExceeded(
                                f"{self.app_id}/{self.deployment}."
                                f"{req.method} shed before dispatch: "
                                f"deadline unreachable (est {est:.3f}s)"
                            )
                        )
                    continue
            group = self._open.setdefault(req.signature, [])
            group.append(req)
            if len(group) >= self.cfg.max_batch:
                self._cancel_timer(req.signature)
                self._dispatch_group(req.signature)
                continue
            wait_budget = self.cfg.max_wait_ms / 1000.0
            if req.deadline is not None:
                est = self.predictor.service_estimate_s()
                wait_budget = min(
                    wait_budget, max(0.0, req.slack(now) - 2.0 * est)
                )
            if wait_budget <= 0.0005:
                # no slack to coalesce — this member's deadline beats
                # batching efficiency
                self._cancel_timer(req.signature)
                self._dispatch_group(req.signature)
                continue
            fire_at = now + wait_budget
            current = self._timer_fire_at.get(req.signature)
            if current is None or fire_at < current - 0.0005:
                # (re-)arm: a deadline-pressed member JOINING an open
                # group pulls its dispatch forward — the coalescing
                # window really is bounded by the tightest member's
                # slack, not just the opener's
                self._cancel_timer(req.signature)
                self._timer_fire_at[req.signature] = fire_at
                self._timers[req.signature] = asyncio.create_task(
                    self._timed_dispatch(req.signature, wait_budget)
                )

    async def _timed_dispatch(self, signature: Hashable, delay: float) -> None:
        try:
            await asyncio.sleep(delay)
            self._timers.pop(signature, None)
            self._timer_fire_at.pop(signature, None)
            self._dispatch_group(signature)
        except asyncio.CancelledError:
            self._timers.pop(signature, None)
            raise

    def _cancel_timer(self, signature: Hashable) -> None:
        task = self._timers.pop(signature, None)
        self._timer_fire_at.pop(signature, None)
        if task:
            task.cancel()

    def _finish_waiting(self, req: _Request) -> None:
        if req.finished_waiting:
            return  # abandonment and dispatch may both reach here
        req.finished_waiting = True
        self.waiting -= 1
        if req.tenant is not None:
            n = self._waiting_by_tenant.get(req.tenant, 1) - 1
            if n <= 0:
                self._waiting_by_tenant.pop(req.tenant, None)
            else:
                self._waiting_by_tenant[req.tenant] = n

    # ---- dispatch -----------------------------------------------------------

    def _dispatch_group(self, signature: Hashable) -> None:
        group = self._open.pop(signature, None)
        if not group:
            return
        now = time.monotonic()
        m_on = metrics.metrics_enabled()
        for r in group:
            self._finish_waiting(r)
            if m_on:
                child = self._m_wait.get(r.priority)
                if child is None:
                    child = self._m_wait[r.priority] = SCHED_QUEUE_WAIT.labels(
                        self.app_id, self.deployment, r.priority
                    )
                child.observe(now - r.enqueued_at)
        self.stats["dispatched_groups"] += 1
        self.stats["dispatched_requests"] += len(group)
        if m_on:
            self._m_dispatch.inc()
            self._m_batch.observe(len(group))
        task = spawn_supervised(
            self._run_group(signature, group),
            name=f"sched-dispatch-{self.app_id}-{self.deployment}",
        )
        self._inflight.add(task)
        task.add_done_callback(self._group_done)

    def _group_done(self, task: asyncio.Task) -> None:
        self._inflight.discard(task)
        self._pump()  # a freed slot may unblock queued work

    async def _run_group(
        self, signature: Hashable, group: list[_Request]
    ) -> None:
        now = time.monotonic()
        now_wall = time.time()
        live: list[_Request] = []
        for r in group:
            if r.trace_ctx is not None:
                wait = now - r.enqueued_at
                tracing.record_span(
                    "sched.queue",
                    wait,
                    started_at=now_wall - wait,
                    parent_id=r.parent_span,
                    ctx=r.trace_ctx,
                    batch_size=len(group),
                    priority=r.priority,
                )
            if r.future.done():
                continue  # caller gave up while queued
            if r.deadline is not None and r.deadline <= now:
                r.future.set_exception(
                    DeadlineExceeded(
                        f"{self.app_id}/{self.deployment}.{r.method} "
                        f"deadline passed while queued"
                    )
                )
                continue
            live.append(r)
        if not live:
            return
        avoid = frozenset().union(*(r.avoid for r in live))
        deadline = None
        if all(r.deadline is not None for r in live):
            deadline = max(r.deadline for r in live)
        try:
            replica = await self._pick_replica_wait(
                signature, avoid, len(live), deadline
            )
        except Exception as e:  # noqa: BLE001 — typed routing errors fan out
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        timeouts = [r.timeout_s for r in live]
        timeout_s = (
            None if any(t is None for t in timeouts) else max(timeouts)
        )
        payload = [{"args": list(r.args), "kwargs": r.kwargs} for r in live]
        t0 = time.monotonic()
        t0_wall = time.time()  # AFTER the pick: spans must not absorb the park
        try:
            items = await replica.call_batch(
                live[0].method, payload, timeout_s=timeout_s
            )
            if len(items) != len(live):
                raise RuntimeError(
                    f"call_batch returned {len(items)} results for "
                    f"{len(live)} requests"
                )
        except Exception as e:  # noqa: BLE001 — classified by the handle's envelope
            # whole-group failure (transport / host gone / budget cut):
            # mirror the direct path's breaker discipline — only a
            # transport-classified failure is replica-health evidence;
            # a caller's expired budget or a client-caused error that
            # died before/inside the frame (APPLICATION kind) is not
            if not is_caller_timeout(e) and is_retryable(e):
                self.controller._breaker_failure(replica, e)
            self._attach_replica(e, replica)
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        wall = time.monotonic() - t0
        self._last_signature[replica.replica_id] = signature
        self._prune_affinity()
        # one outlier-EWMA sample per dispatched group: the group wall
        # is the service time every member experienced on this replica
        self.controller._note_attempt_latency(replica, wall)
        self.predictor.note_service(
            len(live), wall, reground=any(r.probe for r in live)
        )
        breaker_exc = None
        for r, item in zip(live, items):
            if item.get("ok"):
                if not r.future.done():
                    self._record_dispatch_span(r, replica, wall, t0_wall)
                    r.future.set_result(item.get("result"))
                continue
            exc = item.get("exception")
            if exc is None:
                # remote member failure: rebuild on the existing
                # RemoteError wire contract so the handle's
                # classify-by-type-name taxonomy applies unchanged
                exc = RemoteError(
                    item.get("type", "Exception"),
                    item.get("error", "remote batch member failed"),
                )
            # a transport-classified member failure is replica-health
            # evidence even though the frame round-tripped (e.g. the
            # instance's transport raised, or the replica flipped
            # non-routable mid-batch) — but a member's own budget
            # expiring is not
            if not is_caller_timeout(exc) and is_retryable(exc):
                breaker_exc = exc
            self._attach_replica(exc, replica)
            if not r.future.done():
                r.future.set_exception(exc)
        if breaker_exc is not None:
            # ONE failure per dispatch, like one per attempt on the
            # router path — a 16-member batch rejected by a draining
            # replica is one event, not sixteen breaker strikes
            self.controller._breaker_failure(replica, breaker_exc)
        else:
            self.controller._breaker_success(replica)

    @staticmethod
    def _attach_replica(exc: BaseException, replica) -> None:
        """Stamp the serving replica on a member failure so the
        handle's failover loop can avoid it next attempt (the scheduler
        picked the replica, so the handle never saw it)."""
        try:
            exc.replica_id = replica.replica_id
        except (AttributeError, TypeError):
            pass  # slotted/frozen exception types opt out of the hint

    def _record_dispatch_span(
        self, r: _Request, replica, wall: float, started_wall: float
    ) -> None:
        if r.trace_ctx is None:
            return
        tracing.record_span(
            "sched.dispatch",
            wall,
            started_at=started_wall,
            parent_id=r.parent_span,
            ctx=r.trace_ctx,
            replica=replica.replica_id,
        )

    def _prune_affinity(self) -> None:
        """Bound the warm-signature map: replica restarts mint new ids,
        and the map must not grow without bound under churn (swept on a
        size trigger so it runs on every code path, autoscale or not)."""
        if len(self._last_signature) <= 8 + 2 * len(self._all_replicas()):
            return
        live = {r.replica_id for r in self._all_replicas()}
        for rid in [r for r in self._last_signature if r not in live]:
            del self._last_signature[rid]

    async def _pick_replica_wait(
        self,
        signature: Hashable,
        avoid: frozenset,
        group_size: int,
        deadline: Optional[float],
    ):
        """Scored replica choice, waiting through restart windows like
        the router does (same grace/deadline bound, same wakeup)."""
        controller = self.controller
        wait_until = (
            deadline
            if deadline is not None
            else time.monotonic() + controller.pick_replica_grace_s
        )
        while True:
            if controller.apps.get(self.app_id) is None:
                raise NoHealthyReplicasError(
                    f"app '{self.app_id}' is gone"
                )
            best = self._best_replica(signature, avoid, group_size)
            if best is not None:
                return best
            remaining = wait_until - time.monotonic()
            if remaining <= 0:
                raise NoHealthyReplicasError(
                    f"no healthy replicas for "
                    f"{self.app_id}/{self.deployment}"
                )
            controller._replicas_changed.clear()
            try:
                await asyncio.wait_for(
                    controller._replicas_changed.wait(), min(remaining, 0.25)
                )
            except asyncio.TimeoutError:
                pass

    # ---- autoscaling signal -------------------------------------------------

    def _maybe_signal_scale(self, now: float) -> None:
        """Submit-time early trigger (rate-limited): when the projected
        wait crosses the threshold, ring the health loop NOW — the next
        periodic tick may be most of a health period away, which is
        exactly the reactive lag predictive scaling exists to remove."""
        if now - self._last_scale_signal < 1.0:
            return
        n = self._n_routable()
        if n == 0:
            return
        proj = self.predictor.projection(now, self.waiting, n)
        if (
            proj["projected_wait_s"] > self.cfg.target_wait_s
            and len(self._all_replicas()) < self.spec.max_replicas
        ):
            self._last_scale_signal = now
            flight.record(
                "scale.predict",
                app=self.app_id,
                deployment=self.deployment,
                direction="up",
                trigger="submit",
                **{
                    k: proj[k]
                    for k in ("projected_wait_s", "arrival_rate", "service_s")
                },
            )
            self.controller._wake_health.set()

    def _all_replicas(self) -> list:
        app = self.controller.apps.get(self.app_id)
        if app is None:
            return []
        return app.replicas.get(self.deployment, [])

    def scale_decision(self, n_routable: int) -> tuple[str, dict]:
        """The controller's autoscale pass calls this each tick; the
        non-hold verdicts land in the flight ring with the projection
        that justified them."""
        now = time.monotonic()
        decision, proj = self.predictor.decide(
            now,
            self.waiting,
            n_routable,
            self.cfg.target_wait_s,
            self.spec.target_load,
            self.cfg.scale_down_ticks,
        )
        trigger = "tick"
        if self.pressure_fn is not None:
            try:
                pressure = float(self.pressure_fn())
            except Exception:  # noqa: BLE001 — a hook bug must not stop scaling
                pressure = 0.0
            proj["slo_pressure"] = round(pressure, 3)
            if pressure >= 1.0 and decision != "up":
                # the deployment is burning its error budget at page
                # rate: capacity is the one lever the controller holds,
                # whatever the queue projection says (latency burn with
                # shallow queues = slow replicas, not idle ones). ONE
                # event, attributed to the burn — the projection below
                # is the one that said hold.
                decision = "up"
                trigger = "slo_burn"
        if decision != "hold":
            flight.record(
                "scale.predict",
                app=self.app_id,
                deployment=self.deployment,
                direction=decision,
                trigger=trigger,
                **{
                    k: proj[k]
                    for k in (
                        "projected_wait_s",
                        "arrival_rate",
                        "service_s",
                        "utilization",
                        "queue_depth",
                        *(("slo_pressure",) if "slo_pressure" in proj else ()),
                    )
                },
            )
        return decision, proj

    # ---- status / lifecycle -------------------------------------------------

    def describe(self) -> dict:
        now = time.monotonic()
        return {
            "enabled": True,
            "queue_depth": {c: len(q) for c, q in self._queues.items()},
            "open_groups": len(self._open),
            "inflight_groups": len(self._inflight),
            "waiting": self.waiting,
            "stats": dict(self.stats),
            "prediction": self.predictor.projection(
                now, self.waiting, max(1, self._n_routable())
            ),
        }

    def _fail_pending(self, reason: str) -> None:
        """Shared teardown flush: stop the timers, empty every class
        queue and open group, and fail each stranded request typed (so
        idempotent callers fail over / surface cleanly)."""
        self._closed = True
        for signature in list(self._timers):
            self._cancel_timer(signature)
        pending: list[_Request] = []
        for q in self._queues.values():
            pending.extend(q)
            q.clear()
        for group in self._open.values():
            pending.extend(group)
        self._open.clear()
        for r in pending:
            self._finish_waiting(r)
            if not r.future.done():
                r.future.set_exception(
                    ReplicaUnavailableError(
                        f"{self.app_id}/{self.deployment} {reason}"
                    )
                )

    def kill(self) -> None:
        """Crash-path teardown (the scenario engine's SIGKILL
        emulation): the process owning this scheduler is "gone" — every
        queued / open-group request fails typed IMMEDIATELY (exactly
        what a severed client connection would surface) and in-flight
        groups are left to die with their transport. Unlike
        :meth:`close`, nothing is drained: a dead process drains
        nothing."""
        self._fail_pending("control plane died with this request queued")

    async def close(self) -> None:
        """Undeploy path: fail everything still waiting (typed, so
        idempotent callers fail over / surface cleanly) and drain
        in-flight groups — dispatched work finishes against replicas
        the controller is about to drain anyway."""
        self._fail_pending("scheduler closed (undeploy)")
        # bounded, like every other drain in the shutdown path: a group
        # wedged inside a stuck instance must not wedge undeploy — the
        # replica drain/stop that follows owns stranded calls
        deadline = time.monotonic() + DEFAULT_DRAIN_TIMEOUT_S
        while self._inflight:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            done, _ = await asyncio.wait(
                list(self._inflight), timeout=remaining
            )
            if not done:
                break
