"""Controller-managed warm pool — preemption-tolerant standby replicas.

The predictive autoscaler (PR 8) and SLO burn pressure (PR 10) decide
to scale up BEFORE saturation, but the decision is worthless if the new
replica still pays full compile + checkpoint load first. A warm pool
keeps N fully-started standby replicas per deployment — instance built,
``async_init`` run (weights resident), ``test_deployment`` passed (so
programs are compiled wherever the app's self-test exercises them) —
OUT of the routing set. Scale-up and preemption recovery then PROMOTE a
standby (an O(ms) list move + flight event) instead of cold-starting,
and the pool refills in the background.

Config rides the manifest's ``deployment_config.<dep>.warm_pool`` block
(validated typed at build, like ``scheduling:``/``slo:``); sizing can
optionally follow the PR 10 telemetry history (a rising arrival rate
grows the pool toward ``max_size`` before the burst needs it).

Chip accounting: standbys lease chips exactly like serving replicas
(they are warm BECAUSE they sit on real devices), so pool size is a
capacity trade the operator makes explicitly.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from typing import Optional

from bioengine_tpu.utils import flight, metrics

WARMPOOL_PROMOTIONS = metrics.counter(
    "warmpool_promotions_total",
    "standby replicas promoted into the serving set",
    ("app", "deployment"),
)
WARMPOOL_FILLS = metrics.counter(
    "warmpool_fills_total",
    "standby replicas started to (re)fill a warm pool",
    ("app", "deployment"),
)


@dataclass
class WarmPoolConfig:
    """Per-deployment warm-pool knobs (manifest:
    ``deployment_config.<dep>.warm_pool``)."""

    size: int = 1                  # standbys kept ready
    max_size: Optional[int] = None  # telemetry sizing ceiling (None = size)
    # let PR 10 telemetry history grow the pool toward max_size when
    # the deployment's arrival rate is rising (off by default — sizing
    # follows the operator's number unless they opt in)
    telemetry_sized: bool = False
    # refill a promoted/dead standby in the background; off makes the
    # pool one-shot (drain on use), mostly useful in tests
    refill: bool = True

    @classmethod
    def from_config(cls, cfg: dict) -> "WarmPoolConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(cfg) - known)
        if unknown:
            raise ValueError(
                f"unknown warm_pool config keys: {unknown} "
                f"(accepted: {sorted(known)})"
            )
        out = cls()
        if "size" in cfg:
            out.size = int(cfg["size"])
            if out.size < 0:
                raise ValueError("warm_pool.size must be >= 0")
        if "max_size" in cfg and cfg["max_size"] is not None:
            out.max_size = int(cfg["max_size"])
        if "telemetry_sized" in cfg:
            out.telemetry_sized = bool(cfg["telemetry_sized"])
        if "refill" in cfg:
            out.refill = bool(cfg["refill"])
        if out.max_size is not None and out.max_size < out.size:
            raise ValueError(
                f"warm_pool.max_size ({out.max_size}) < size ({out.size})"
            )
        return out


class WarmPool:
    """The standby set for one deployment. The controller owns all
    placement/teardown; this class owns membership and accounting."""

    def __init__(self, app_id: str, deployment: str, config: WarmPoolConfig):
        self.app_id = app_id
        self.deployment = deployment
        self.config = config
        self.standbys: list = []        # Replica | RemoteReplica, all started
        # standbys currently being PLACED (cold start in flight, not yet
        # in standbys) — counted against target so a promotion-triggered
        # refill and the health tick can't both fill the same slot
        self.filling = 0
        self.promotions = 0
        self.fills = 0
        self.fill_failures = 0
        self.last_promotion_at: Optional[float] = None
        self._m_promotions = WARMPOOL_PROMOTIONS.labels(app_id, deployment)
        self._m_fills = WARMPOOL_FILLS.labels(app_id, deployment)

    # ---- membership ---------------------------------------------------------

    def add(self, replica) -> None:
        self.standbys.append(replica)
        self.fills += 1
        self._m_fills.inc()
        flight.record(
            "warmpool.fill",
            app=self.app_id,
            deployment=self.deployment,
            replica=replica.replica_id,
            host=getattr(replica, "host_id", None),
            occupancy=len(self.standbys),
        )

    def pop_routable(self, skip_hosts: Optional[set] = None):
        """Take the first routable standby (oldest first — it has been
        warm longest), or None. ``skip_hosts`` excludes standbys whose
        host the controller already knows is dead — promoting one would
        hand traffic a black hole whose health check hasn't run yet.
        Records the promotion; the caller moves it into the serving set
        and emits ``replica.place``."""
        from bioengine_tpu.serving.replica import ROUTABLE_STATES

        for i, replica in enumerate(self.standbys):
            if (
                skip_hosts
                and getattr(replica, "host_id", None) in skip_hosts
            ):
                continue
            if replica.state in ROUTABLE_STATES:
                self.standbys.pop(i)
                self.promotions += 1
                self._m_promotions.inc()
                self.last_promotion_at = time.time()
                if hasattr(replica, "mark_promoted"):
                    replica.mark_promoted()
                flight.record(
                    "warmpool.promote",
                    app=self.app_id,
                    deployment=self.deployment,
                    replica=replica.replica_id,
                    host=getattr(replica, "host_id", None),
                    standby_seconds=replica.ttfr.get("standby_seconds"),
                    occupancy=len(self.standbys),
                )
                return replica
        return None

    def remove_dead(self) -> list:
        """Drop (and return) standbys that went non-routable — the
        controller releases their leases and refills."""
        from bioengine_tpu.serving.replica import ROUTABLE_STATES

        dead = [r for r in self.standbys if r.state not in ROUTABLE_STATES]
        if dead:
            self.standbys = [
                r for r in self.standbys if r.state in ROUTABLE_STATES
            ]
        return dead

    def drain_all(self) -> list:
        out, self.standbys = self.standbys, []
        return out

    # ---- sizing -------------------------------------------------------------

    def target_size(self, telemetry=None) -> int:
        """The size this pool should hold right now. With
        ``telemetry_sized`` and a history store, a rising request rate
        (latest base-resolution bucket vs the window mean) grows the
        target toward ``max_size`` so the pool is already deep when the
        autoscaler fires."""
        base = self.config.size
        ceiling = (
            self.config.max_size
            if self.config.max_size is not None
            else base
        )
        if not self.config.telemetry_sized or telemetry is None:
            return base
        try:
            series = telemetry.series(
                self.app_id, self.deployment, "request_rate"
            )
            # zero-rate buckets are DATA, not gaps: an idle-then-burst
            # deployment needs its idle zeros in the mean for the burst
            # to register as a spike (and a just-gone-idle latest bucket
            # of 0 must read as "no burst", not inherit an old value)
            points = [
                p["value"]
                for p in (series or [])
                if p.get("value") is not None
            ]
        except Exception:  # noqa: BLE001 — sizing never breaks the health tick
            return base
        if len(points) < 3:
            return base
        mean = sum(points) / len(points)
        if mean > 0 and points[-1] > 1.5 * mean:
            return min(base + 1, ceiling)
        return base

    def stats(self) -> dict:
        return {
            "occupancy": len(self.standbys),
            "filling": self.filling,
            "target": self.config.size,
            "max_size": self.config.max_size,
            "telemetry_sized": self.config.telemetry_sized,
            "promotions": self.promotions,
            "fills": self.fills,
            "fill_failures": self.fill_failures,
            "last_promotion_at": self.last_promotion_at,
            "standby_replicas": [r.replica_id for r in self.standbys],
        }
