"""Router tier — the request path, split out of the controller.

Every request used to funnel through the single ``ServeController``
process; that process was the ceiling no chip count could raise. This
module is the horizontal half of the fix (ROADMAP item 2): the entire
request path — replica pick/score, the ``DeploymentHandle`` retry loop,
hedging, the circuit breaker, outlier probation, scheduler attach —
lives in :class:`RouterCore`, a mixin BOTH planes speak:

- ``ServeController(RouterCore)`` keeps the in-process path
  bit-compatible: same attribute names, same methods, same metrics.
- :class:`StandaloneRouter` is ``RouterCore`` over a locally cached,
  epoch-stamped **routing table** instead of live placement state. N of
  them scale the data plane out while the controller shrinks to
  intent + placement + table publication.

The routing table (``bioengine.routing-table/v1``) carries the replica
set with lifecycle states, mesh/host membership, per-deployment
scheduler configs, and breaker/probation hints. The controller's
:class:`RoutingTablePublisher` versions it monotonically and serves
diffs (``since_version``) over the existing RPC plane
(``serve-router.get_routing_table``); every table is stamped with the
PR 15 journal epoch, so a wedged-then-revived old controller's push is
rejected typed (:class:`~bioengine_tpu.serving.errors.StaleTableError`)
and can never regress a router's newer view. A router keeps serving
from its last-good table through a controller crash/restart and
reports the table's staleness age (``router_table_staleness_seconds``).

Failure model: routers are stateless per request. Killing one loses
nothing — its gate refuses new requests typed-retryable
(:class:`~bioengine_tpu.serving.errors.RouterClosedError`), so clients
fail over to a sibling router through the same PR 4 typed-retry
machinery that fails requests over between replicas. The ``router_loss``
scenario pins that at zero idempotent-request loss.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import time
from dataclasses import dataclass, field
from collections import defaultdict
from typing import Any, Callable, Optional

from bioengine_tpu.rpc.protocol import RemoteError
from bioengine_tpu.serving.errors import (
    AdmissionRejectedError,
    DeadlineExceeded,
    FailureKind,
    NoHealthyReplicasError,
    ReplicaUnavailableError,
    RetryableTransportError,
    RouterClosedError,
    RouterSaturatedError,
    StaleTableError,
    classify_exception,
    is_caller_timeout,
    is_retryable,
)
from bioengine_tpu.serving.outlier import (
    DeploymentLatencyTracker,
    OutlierConfig,
    REPLICA_PROBATIONS,
    record_probation_event,
)
from bioengine_tpu.serving.remote import RemoteReplica
from bioengine_tpu.serving.replica import (
    ROUTABLE_STATES,
    Replica,
    ReplicaState,
)
from bioengine_tpu.serving.scheduler import (
    DeploymentScheduler,
    HeuristicCostModel,
    SchedulingConfig,
)
from bioengine_tpu.utils import flight, metrics, tracing
from bioengine_tpu.utils.backoff import full_jitter_delay
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.tasks import spawn_supervised

TABLE_SCHEMA = "bioengine.routing-table/v1"

# ---- request-path metrics (process-wide, utils/metrics.py) ---------------
# e2e latency is what the SLO dashboard reads; outcome/failover counters
# are what the future global scheduler keys on (ROADMAP item 1)
REQUEST_E2E = metrics.histogram(
    "request_e2e_seconds",
    "end-to-end DeploymentHandle.call latency (route + retries + execute)",
    ("app", "deployment", "method"),
)
REQUEST_OUTCOMES = metrics.counter(
    "requests_total",
    "completed DeploymentHandle.call requests by outcome",
    ("app", "deployment", "outcome"),
)
REQUEST_FAILOVERS = metrics.counter(
    "request_failovers_total",
    "attempts retried on another replica after a transport failure",
    ("app", "deployment"),
)
ROUTE_WAIT = metrics.histogram(
    "route_wait_seconds",
    "time spent picking (or waiting for) a routable replica",
    ("app", "deployment"),
)
BREAKER_TRIPS = metrics.counter(
    "breaker_trips_total",
    "circuit-breaker ejections (replica marked UNHEALTHY)",
    ("app", "deployment"),
)
REQUEST_HEDGES = metrics.counter(
    "request_hedges_total",
    "hedge attempts launched for idempotent calls, by winning attempt",
    ("app", "deployment", "winner"),
)
# token-streaming request path (DeploymentHandle.call_stream):
# inter_token_seconds is the generative-serving SLO signal (slo.py's
# inter_token_ms objective reads its buckets) — the FIRST item's gap is
# time-to-first-token and lands in ttft_seconds instead, so inter-token
# percentiles aren't polluted by prefill+route time
TOKENS_GENERATED = metrics.counter(
    "tokens_generated_total",
    "stream items yielded to callers by DeploymentHandle.call_stream",
    ("app", "deployment"),
)
INTER_TOKEN = metrics.histogram(
    "inter_token_seconds",
    "gap between consecutive stream items at the caller edge",
    ("app", "deployment"),
)
TTFT = metrics.histogram(
    "ttft_seconds",
    "call_stream start to first item (route + prefill + first frame)",
    ("app", "deployment"),
)
STREAM_RESUMES = metrics.counter(
    "stream_resumes_total",
    "mid-stream failovers resumed on another replica (idempotent calls)",
    ("app", "deployment"),
)


@dataclass(frozen=True)
class RequestOptions:
    """Per-request envelope for ``DeploymentHandle.call``.

    ``deadline_s`` bounds the WHOLE request (every attempt + backoff);
    ``timeout_s`` bounds one attempt and is propagated to the serving
    host so remote work is aborted there too. ``idempotent`` opts the
    call into transparent failover: transport/placement errors retry
    on another healthy replica with exponential backoff + full jitter.
    Non-idempotent calls surface the first transport error exactly
    once, typed (``RetryableTransportError``) — never silently retried,
    because the outcome on the dead replica is ambiguous.

    ``priority`` and ``tenant`` only matter on deployments with a
    global scheduler attached: the priority class picks the
    weighted-fair queue (``interactive`` / ``bulk`` / ``background`` by
    default) and the tenant id counts against the per-tenant admission
    quota.

    ``hedge`` opts an **idempotent** call into request hedging (the
    gray-failure tail defense): when the first attempt is still
    running after a p95-derived delay (override: ``hedge_delay_s``), a
    second attempt launches on a DIFFERENT replica; the first result
    wins and the loser is cancelled — never counted against the
    breaker or the latency outlier detector (a loser cancelled by the
    winner is not replica-failure evidence). Hedging a non-idempotent
    call would double side effects, so that combination is rejected at
    construction — hedges can never fire for non-idempotent calls.
    Hedging applies to ROUTER-path deployments only: on a deployment
    with a ``scheduling:`` config the global scheduler owns placement
    (probation rides its scorer feature dict instead) and ``hedge`` is
    ignored."""

    timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None
    idempotent: bool = False
    max_attempts: int = 4
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    priority: Optional[str] = None     # scheduler class; None = default
    tenant: Optional[str] = None       # admission quota bucket
    hedge: bool = False                # idempotent-only tail hedging
    hedge_delay_s: Optional[float] = None  # None = deployment p95

    def __post_init__(self):
        if self.hedge and not self.idempotent:
            raise ValueError(
                "RequestOptions(hedge=True) requires idempotent=True — "
                "a hedge is a silent second execution, which a "
                "non-idempotent call can never tolerate"
            )

    @classmethod
    def from_env(cls) -> "RequestOptions":
        env = os.environ.get
        return cls(
            max_attempts=int(env("BIOENGINE_REQUEST_MAX_ATTEMPTS", "4")),
            backoff_base_s=float(env("BIOENGINE_REQUEST_BACKOFF_BASE_S", "0.05")),
            backoff_cap_s=float(env("BIOENGINE_REQUEST_BACKOFF_CAP_S", "2.0")),
        )

    @classmethod
    def defaults(cls) -> "RequestOptions":
        """Env-derived defaults, read once (this sits on the hot path)."""
        global _DEFAULT_OPTIONS
        if _DEFAULT_OPTIONS is None:
            _DEFAULT_OPTIONS = cls.from_env()
        return _DEFAULT_OPTIONS


_DEFAULT_OPTIONS: Optional[RequestOptions] = None


class DeploymentHandle:
    """Client-side handle: route calls to healthy replicas (least-loaded,
    round-robin tie-break). The composition mechanism: entry deployments
    receive handles to their sibling deployments as init kwargs, same as
    the reference's DeploymentHandle binding (ref apps/builder.py:1474-1508).

    Fault tolerance: each call runs under a :class:`RequestOptions`
    envelope (pass ``options=RequestOptions(...)`` per call, or bind
    defaults with :meth:`with_options`). Transport/placement failures on
    idempotent calls fail over to another replica; during a restart
    window the router WAITS (bounded by the deadline) for a healthy
    replica instead of raising instantly.

    ``controller`` is any :class:`RouterCore` — the in-process
    ``ServeController`` or a :class:`StandaloneRouter`; the handle is
    identical either way (that IS the seam)."""

    def __init__(
        self,
        controller: "RouterCore",
        app_id: str,
        deployment: str,
        options: Optional[RequestOptions] = None,
    ):
        self._controller = controller
        self.app_id = app_id
        self.deployment = deployment
        self._options = options
        self._rr = itertools.count()
        # labeled children resolved once — labels() costs a few us of
        # str()/tuple/lock per lookup, paid per request otherwise
        self._m_route_wait = ROUTE_WAIT.labels(app_id, deployment)
        self._m_failovers = REQUEST_FAILOVERS.labels(app_id, deployment)
        self._m_e2e: dict[str, Any] = {}       # method -> histogram child
        self._m_outcomes: dict[str, Any] = {}  # outcome -> counter child
        self._m_hedges: dict[str, Any] = {}    # winner -> counter child
        # prebuilt span-attr template: the route span's attrs never
        # change for a handle, so the unsampled hot path must not
        # allocate a kwargs dict per request just to throw it away
        self._ts_route = {"app": app_id, "deployment": deployment}

    def with_options(self, options: RequestOptions) -> "DeploymentHandle":
        """A sibling handle whose calls default to ``options``."""
        return DeploymentHandle(
            self._controller, self.app_id, self.deployment, options
        )

    async def call(self, method: str, *args, **kwargs) -> Any:
        # the envelope rides a reserved kwarg, but ONLY when it is an
        # actual RequestOptions — an app method's own `options` kwarg
        # passes through untouched
        options = kwargs.pop("options", None)
        if options is not None and not isinstance(options, RequestOptions):
            kwargs["options"] = options
            options = None
        options = options or self._options or RequestOptions.defaults()

        # Observability wrapper. A trace context is minted here (the
        # client edge of the serve path) and rides the contextvar
        # through routing, the RPC envelope (capability-negotiated),
        # the host's replica, batcher, and engine — get_traces
        # reassembles one cross-process tree per trace_id. Head
        # sampling (BIOENGINE_TRACE_SAMPLE) keeps the unsampled path
        # at one id mint + a few counter bumps; BIOENGINE_TRACING=0
        # removes even that (the bench's baseline leg) — but metrics
        # and slow-request logging have their OWN knobs and keep
        # working with tracing off. If a sampled trace is ALREADY
        # active (a composition call routed back through serve-router),
        # nest under it instead of minting.
        parent = tracing.current_trace()
        ctx = parent if parent is not None else tracing.maybe_start_trace()
        token = (
            tracing.activate(ctx)
            if ctx is not None and parent is None
            else None
        )
        # standalone routers gate admission here (closed → typed
        # failover to a sibling router; saturated → typed shed); the
        # in-process controller keeps the gate at None, so its cost on
        # that path is one attribute load and a None check
        gate = self._controller._router_gate
        entered = False
        t0 = time.monotonic()
        outcome = "ok"
        try:
            if gate is not None:
                gate.enter()
                entered = True
            if ctx is not None and ctx.sampled:
                with tracing.span(
                    "request",
                    app=self.app_id,
                    deployment=self.deployment,
                    method=method,
                    trace_root=parent is None,
                ) as record:
                    result = await self._call_attempts(
                        method, args, kwargs, options
                    )
                    # per-request device cost on the TRACE ROOT: the sum
                    # of every engine.predict under this trace_id (local
                    # spans plus the ones absorbed off RESULT frames),
                    # each already engine wall-seconds x mesh width.
                    # Nested composition spans don't stamp — the whole
                    # trace's cost belongs to exactly one root.
                    if parent is None:
                        cs = tracing.trace_attr_sum(
                            ctx.trace_id, "engine.predict", "chip_seconds"
                        )
                        if cs:
                            record["attrs"]["chip_seconds"] = round(cs, 6)
                    return result
            return await self._call_attempts(method, args, kwargs, options)
        except Exception as e:
            kind = classify_exception(e)
            outcome = {
                FailureKind.APPLICATION: "app_error",
                FailureKind.DEADLINE: "deadline",
            }.get(kind, "transport_error")
            if isinstance(e, AdmissionRejectedError):
                # load shedding is its own outcome: an SLO dashboard
                # must tell "we said no" apart from "the app broke"
                outcome = "rejected"
            if kind is FailureKind.DEADLINE:
                # the evidence of WHY the budget was blown (breaker
                # trips, re-placements, parks) is in the ring right now
                # — snapshot it before it wraps
                flight.record(
                    "deadline.exceeded",
                    severity="error",
                    app=self.app_id,
                    deployment=self.deployment,
                    method=method,
                    trace_id=ctx.trace_id if ctx else None,
                    error=str(e)[:500],
                )
                flight.dump(
                    "deadline_exceeded",
                    app=self.app_id,
                    deployment=self.deployment,
                )
            raise
        finally:
            if entered:
                gate.leave()
            duration = time.monotonic() - t0
            if token is not None:
                tracing.deactivate(token)
            if metrics.metrics_enabled():
                e2e = self._m_e2e.get(method)
                if e2e is None:
                    e2e = self._m_e2e[method] = REQUEST_E2E.labels(
                        self.app_id, self.deployment, method
                    )
                e2e.observe(duration)
                out_c = self._m_outcomes.get(outcome)
                if out_c is None:
                    out_c = self._m_outcomes[outcome] = REQUEST_OUTCOMES.labels(
                        self.app_id, self.deployment, outcome
                    )
                out_c.inc()
            slow_ms = tracing.slow_request_threshold_ms()
            if slow_ms > 0 and duration * 1000.0 >= slow_ms:
                # structured + trace_id-stamped: grep the log line,
                # then get_traces(trace_id=...) for the breakdown
                # (trace_id=- when tracing is globally disabled)
                self._controller.logger.warning(
                    "slow_request "
                    f"trace_id={ctx.trace_id if ctx else '-'} "
                    f"app={self.app_id} "
                    f"deployment={self.deployment} method={method} "
                    f"duration_ms={duration * 1000.0:.1f} "
                    f"outcome={outcome} "
                    f"sampled={ctx.sampled if ctx else False}"
                )
                flight.record(
                    "request.slow",
                    severity="warning",
                    app=self.app_id,
                    deployment=self.deployment,
                    method=method,
                    duration_ms=round(duration * 1000.0, 1),
                    outcome=outcome,
                    trace_id=ctx.trace_id if ctx else None,
                )

    async def call_stream(self, method: str, *args, **kwargs):
        """Streaming twin of :meth:`call`: routes to one replica and
        yields items (tokens) as they arrive. Streams bypass the
        request scheduler's coalescing — step-level batching happens
        INSIDE the replica's decode loop (serving/decode.py), which is
        the whole point — but reuse the same replica pick, breaker
        bookkeeping, and failover discipline.

        Mid-stream transport failure on an idempotent call resumes on
        another replica with ``resume_from=<items already yielded>``:
        greedy decoding is deterministic, so the new replica regenerates
        and skips the prefix — the caller sees an uninterrupted,
        exactly-once token sequence (``decode.stream_resume`` in the
        flight ring marks the seam). Non-idempotent streams fail typed
        instead. Application errors are never retried."""
        options = kwargs.pop("options", None)
        if options is not None and not isinstance(options, RequestOptions):
            kwargs["options"] = options
            options = None
        options = options or self._options or RequestOptions.defaults()

        parent = tracing.current_trace()
        ctx = parent if parent is not None else tracing.maybe_start_trace()
        token = (
            tracing.activate(ctx)
            if ctx is not None and parent is None
            else None
        )
        m_on = metrics.metrics_enabled()
        deadline = (
            time.monotonic() + options.deadline_s
            if options.deadline_s is not None
            else None
        )
        tried: set[str] = set()
        yielded = 0
        base_resume = int(kwargs.get("resume_from", 0) or 0)
        attempt = 0
        t0 = time.monotonic()
        t_last: Optional[float] = None
        outcome = "ok"
        try:
            while True:
                attempt += 1
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise DeadlineExceeded(
                        f"deadline exhausted after {attempt - 1} attempt(s) "
                        f"for {self.app_id}/{self.deployment}.{method}"
                    )
                t_route = time.monotonic()
                with tracing.trace_span_t("route", self._ts_route):
                    replica = await self._controller._pick_replica_wait(
                        self.app_id, self.deployment, avoid=tried,
                        deadline=deadline,
                    )
                if m_on:
                    self._m_route_wait.observe(time.monotonic() - t_route)
                attempt_kwargs = kwargs
                if yielded > 0:
                    attempt_kwargs = dict(kwargs)
                    attempt_kwargs["resume_from"] = base_resume + yielded
                got_any_this_attempt = False
                try:
                    with (
                        tracing.span(
                            "stream_attempt",
                            replica=replica.replica_id,
                            attempt=attempt,
                        )
                        if tracing.sampled()
                        else tracing.NOOP_SPAN
                    ):
                        async for item in replica.call_stream(
                            method, *args, **attempt_kwargs
                        ):
                            now = time.monotonic()
                            if yielded == 0:
                                if m_on:
                                    self._m_ttft().observe(now - t0)
                            elif t_last is not None and m_on:
                                self._m_inter_token().observe(now - t_last)
                            t_last = now
                            yielded += 1
                            got_any_this_attempt = True
                            if m_on:
                                self._m_tokens().inc()
                            yield item
                    self._controller._breaker_success(replica)
                    return
                except Exception as e:
                    kind = classify_exception(e)
                    if kind is FailureKind.APPLICATION:
                        raise
                    if not is_caller_timeout(e):
                        self._controller._breaker_failure(replica, e)
                    tried.add(replica.replica_id)
                    if isinstance(e, DeadlineExceeded):
                        raise
                    remaining = (
                        None
                        if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        raise DeadlineExceeded(
                            f"deadline exhausted after {attempt} attempt(s): {e}"
                        ) from e
                    # once items have been yielded, ONLY an idempotent
                    # stream may resume (deterministic regeneration);
                    # before first item the not-executed rule applies
                    not_executed = isinstance(
                        e, ReplicaUnavailableError
                    ) and not isinstance(e, RemoteError)
                    if not options.idempotent and not (
                        not_executed and not got_any_this_attempt
                    ):
                        raise RetryableTransportError(
                            f"{self.app_id}/{self.deployment}.{method} "
                            f"stream failed on {replica.replica_id} after "
                            f"{yielded} item(s) (non-idempotent, not "
                            f"resumed): {e}"
                        ) from e
                    if attempt >= options.max_attempts:
                        raise RetryableTransportError(
                            f"{self.app_id}/{self.deployment}.{method} "
                            f"stream failed after {attempt} attempts "
                            f"({yielded} item(s) delivered): {e}"
                        ) from e
                    if m_on:
                        self._m_failovers.inc()
                    if yielded > 0:
                        if m_on:
                            self._m_resumes().inc()
                        flight.record(
                            "decode.stream_resume",
                            severity="warning",
                            app=self.app_id,
                            deployment=self.deployment,
                            method=method,
                            replica=replica.replica_id,
                            resume_from=base_resume + yielded,
                            attempt=attempt,
                            error=str(e)[:300],
                        )
                    else:
                        flight.record(
                            "request.failover",
                            severity="warning",
                            app=self.app_id,
                            deployment=self.deployment,
                            method=method,
                            replica=replica.replica_id,
                            attempt=attempt,
                            error=str(e)[:300],
                        )
                    delay = full_jitter_delay(
                        attempt - 1,
                        options.backoff_base_s,
                        options.backoff_cap_s,
                    )
                    if remaining is not None:
                        delay = min(delay, max(0.0, remaining))
                    await asyncio.sleep(delay)
        except Exception as e:
            kind = classify_exception(e)
            outcome = {
                FailureKind.APPLICATION: "app_error",
                FailureKind.DEADLINE: "deadline",
            }.get(kind, "transport_error")
            raise
        finally:
            if token is not None:
                tracing.deactivate(token)
            if m_on:
                e2e = self._m_e2e.get(method)
                if e2e is None:
                    e2e = self._m_e2e[method] = REQUEST_E2E.labels(
                        self.app_id, self.deployment, method
                    )
                e2e.observe(time.monotonic() - t0)
                out_c = self._m_outcomes.get(outcome)
                if out_c is None:
                    out_c = self._m_outcomes[outcome] = REQUEST_OUTCOMES.labels(
                        self.app_id, self.deployment, outcome
                    )
                out_c.inc()

    # stream-metric children resolved lazily (streams are opt-in per
    # deployment — a unary-only handle never materializes them)
    def _m_tokens(self):
        child = self.__dict__.get("_m_tokens_c")
        if child is None:
            child = self.__dict__["_m_tokens_c"] = TOKENS_GENERATED.labels(
                self.app_id, self.deployment
            )
        return child

    def _m_inter_token(self):
        child = self.__dict__.get("_m_inter_token_c")
        if child is None:
            child = self.__dict__["_m_inter_token_c"] = INTER_TOKEN.labels(
                self.app_id, self.deployment
            )
        return child

    def _m_ttft(self):
        child = self.__dict__.get("_m_ttft_c")
        if child is None:
            child = self.__dict__["_m_ttft_c"] = TTFT.labels(
                self.app_id, self.deployment
            )
        return child

    def _m_resumes(self):
        child = self.__dict__.get("_m_resumes_c")
        if child is None:
            child = self.__dict__["_m_resumes_c"] = STREAM_RESUMES.labels(
                self.app_id, self.deployment
            )
        return child

    async def _call_attempts(
        self, method: str, args: tuple, kwargs: dict, options: RequestOptions
    ) -> Any:
        deadline = (
            time.monotonic() + options.deadline_s
            if options.deadline_s is not None
            else None
        )
        key = (self.app_id, self.deployment)
        tried: set[str] = set()
        attempt = 0
        while True:
            attempt += 1
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise DeadlineExceeded(
                    f"deadline exhausted after {attempt - 1} attempt(s) "
                    f"for {self.app_id}/{self.deployment}.{method}"
                )
            scheduler = self._controller._schedulers.get(key)
            replica = None
            if scheduler is None:
                t_route = time.monotonic()
                with tracing.trace_span_t("route", self._ts_route):
                    replica = await self._controller._pick_replica_wait(
                        self.app_id, self.deployment, avoid=tried,
                        deadline=deadline,
                    )
                if metrics.metrics_enabled():
                    self._m_route_wait.observe(time.monotonic() - t_route)
                # the wait above may have parked through most of the
                # budget — recompute so the attempt (and the host-side
                # timeout it propagates) cannot overrun the deadline
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DeadlineExceeded(
                            f"deadline exhausted while waiting for a replica "
                            f"of {self.app_id}/{self.deployment}"
                        )
            budget = _min_defined(options.timeout_s, remaining)
            self._controller._queue_depth[key] += 1
            # hedged attempts do their own breaker/latency bookkeeping
            # per sub-attempt (a cancelled loser must feed NEITHER) —
            # the outer handlers skip theirs to avoid double counting
            hedged = (
                scheduler is None
                and replica is not None
                and options.hedge
                and options.idempotent
            )
            try:
                if hedged:
                    result = await self._hedged_attempt(
                        replica, method, args, kwargs, options,
                        budget, deadline, tried, attempt,
                    )
                    return result
                # attempt attrs vary per call — gate the kwargs-dict
                # build on the sampled check instead of templating
                with (
                    tracing.span(
                        "attempt",
                        replica=replica.replica_id
                        if replica
                        else "scheduler",
                        attempt=attempt,
                    )
                    if tracing.sampled()
                    else tracing.NOOP_SPAN
                ):
                    if scheduler is None:
                        t_attempt = time.monotonic()
                        result = await replica.call_bounded(
                            method, args, kwargs, timeout_s=budget
                        )
                        # successful-attempt service time feeds the
                        # gray-failure outlier EWMA (failures measure
                        # the transport, not the replica)
                        self._controller._note_attempt_latency(
                            replica, time.monotonic() - t_attempt
                        )
                    else:
                        # the scheduler owns admission, fair queueing,
                        # group coalescing, and the scored replica pick
                        # for this attempt; breaker bookkeeping happens
                        # inside its dispatch (it saw the replica, we
                        # did not)
                        result = await scheduler.submit(
                            method,
                            args,
                            kwargs,
                            options=options,
                            timeout_s=budget,
                            deadline=deadline,
                            avoid=frozenset(tried),
                        )
                if replica is not None:
                    self._controller._breaker_success(replica)
                return result
            except Exception as e:
                kind = classify_exception(e)
                if kind is FailureKind.APPLICATION:
                    raise  # the app ran and failed — never retried
                # a timeout of the CALLER's own budget says nothing
                # about replica health — only genuine transport/placement
                # failures feed the circuit breaker
                if (
                    replica is not None
                    and not hedged
                    and not is_caller_timeout(e)
                ):
                    self._controller._breaker_failure(replica, e)
                # scheduler-dispatched failures stamp the serving
                # replica on the exception so failover can avoid it
                rid = (
                    replica.replica_id
                    if replica is not None
                    else getattr(e, "replica_id", None)
                )
                if rid is not None:
                    tried.add(rid)
                if isinstance(e, DeadlineExceeded):
                    raise
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    # the overall budget is gone — surface it AS a
                    # deadline on every path (a non-idempotent attempt
                    # whose timeout WAS the deadline cut included)
                    raise DeadlineExceeded(
                        f"deadline exhausted after {attempt} attempt(s): {e}"
                    ) from e
                # a LOCAL ReplicaUnavailableError was raised by the
                # routability check BEFORE anything was sent — zero
                # ambiguity, so even non-idempotent calls fail over
                not_executed = isinstance(
                    e, ReplicaUnavailableError
                ) and not isinstance(e, RemoteError)
                if not options.idempotent and not not_executed:
                    raise RetryableTransportError(
                        f"{self.app_id}/{self.deployment}.{method} failed in "
                        f"transport on {rid or 'scheduler'} (non-idempotent "
                        f"call, not retried): {e}"
                    ) from e
                if attempt >= options.max_attempts:
                    raise RetryableTransportError(
                        f"{self.app_id}/{self.deployment}.{method} failed "
                        f"after {attempt} attempts: {e}"
                    ) from e
                if metrics.metrics_enabled():
                    self._m_failovers.inc()
                flight.record(
                    "request.failover",
                    severity="warning",
                    app=self.app_id,
                    deployment=self.deployment,
                    method=method,
                    replica=rid,
                    attempt=attempt,
                    error=str(e)[:300],
                )
                # exponential backoff with FULL jitter, clamped to the
                # remaining deadline budget
                delay = full_jitter_delay(
                    attempt - 1, options.backoff_base_s, options.backoff_cap_s
                )
                if remaining is not None:
                    delay = min(delay, max(0.0, remaining))
                await asyncio.sleep(delay)
            finally:
                # router-state leak discipline: undeploy sweeps this
                # entry, but an in-flight retry's increment (defaultdict)
                # can resurrect it — so the decrement clamps at zero
                # (never a persistent negative, even when old-generation
                # decrements interleave with a redeploy) and a key whose
                # app is gone is swept here instead of lingering
                depth = self._controller._queue_depth
                if key in depth:
                    if depth[key] > 0:
                        depth[key] -= 1
                    if (
                        depth[key] <= 0
                        and self.app_id not in self._controller.apps
                    ):
                        depth.pop(key, None)

    # ---- request hedging (gray-failure tail defense) ------------------------

    async def _hedged_attempt(
        self,
        primary,
        method: str,
        args: tuple,
        kwargs: dict,
        options: RequestOptions,
        budget: Optional[float],
        deadline: Optional[float],
        tried: set,
        attempt: int,
    ) -> Any:
        """One attempt with tail hedging: run on ``primary``; if it is
        still in flight after the p95-derived delay, launch the SAME
        call on a different replica — first result wins, the loser is
        cancelled. Only reachable for idempotent calls (RequestOptions
        enforces that at construction; the router re-checks).

        Bookkeeping discipline — the satellite bug this pins: the
        cancelled loser feeds NEITHER the circuit breaker NOR the
        outlier EWMA (a loser cancelled by the winner is not replica-
        failure evidence, the same class of bug as the caller-budget
        breaker exemption). Only genuinely-failed sub-attempts strike
        the breaker; only the winner's wall time feeds the EWMA. Both
        sub-attempts open sibling ``attempt`` spans under the one
        trace_id, so `get_traces` shows the hedge as two children of
        the same request."""
        controller = self._controller

        async def run(target, label: str, timeout_s: Optional[float]):
            t0 = time.monotonic()
            # span opened INSIDE the task: each sub-attempt becomes its
            # own sibling under the request/route span (create_task
            # copies the context, so both inherit the same parent)
            with tracing.trace_span(
                "attempt",
                replica=target.replica_id,
                attempt=attempt,
                hedge=label,
            ):
                result = await target.call_bounded(
                    method, args, kwargs, timeout_s=timeout_s
                )
            return result, time.monotonic() - t0

        # a probe-routed request (primary in PROBATION) is the trickle
        # the recovery loop lives on: it hedges AT ONCE (delay 0 — the
        # probe exists to measure the replica, not to make one unlucky
        # caller pay the gray-latency tax), and on any exit the probe
        # attempt is DETACHED to finish in the background instead of
        # cancelled — cancelling it would throw away the one latency
        # measurement the probe exists to take, freezing the replica
        # in probation forever once every caller hedges. Bounded by
        # the attempt's own timeout budget; chip/semaphore accounting
        # settles on its normal completion path.
        probing = primary.state == ReplicaState.PROBATION
        t_primary = asyncio.create_task(run(primary, "primary", budget))
        t_hedge: Optional[asyncio.Task] = None
        detached: set = set()

        async def resolve_primary_only() -> Any:
            try:
                result, dt = await t_primary
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # same breaker discipline as the scheduler paths: only
                # TRANSPORT-classified failures are replica-health
                # evidence — an app error (bad client input) or the
                # caller's own budget expiring must never eject a
                # healthy replica
                if not is_caller_timeout(exc) and is_retryable(exc):
                    controller._breaker_failure(primary, exc)
                raise
            controller._note_attempt_latency(primary, dt)
            controller._breaker_success(primary)
            return result

        # ONE try/finally owns both attempt tasks for the whole hedged
        # call: a caller cancellation anywhere in here (wait_for around
        # handle.call, client disconnect) must cancel the in-flight
        # attempts too — cancelling the awaiter never cancels a Task
        try:
            delay = (
                0.0
                if probing
                else controller.hedge_delay_s(
                    self.app_id, self.deployment, options
                )
            )
            done, _ = await asyncio.wait({t_primary}, timeout=delay)
            if done:
                # resolved inside the hedge window — no hedge needed;
                # this path costs one asyncio.wait over a direct await
                return await resolve_primary_only()
            try:
                hedge_replica = controller._pick_replica(
                    self.app_id,
                    self.deployment,
                    avoid=set(tried) | {primary.replica_id},
                )
            except (NoHealthyReplicasError, KeyError):
                hedge_replica = None
            hedge_budget = budget
            if deadline is not None:
                hedge_budget = _min_defined(
                    options.timeout_s, deadline - time.monotonic()
                )
                if hedge_budget is not None and hedge_budget <= 0:
                    hedge_replica = None
            if (
                hedge_replica is None
                or hedge_replica.replica_id == primary.replica_id
            ):
                # nobody distinct to hedge on (single-replica
                # deployment, or everything else already tried) — ride
                # the primary
                return await resolve_primary_only()
            t_hedge = asyncio.create_task(
                run(hedge_replica, "hedge", hedge_budget)
            )
            owners = {t_primary: primary, t_hedge: hedge_replica}
            primary_exc: Optional[BaseException] = None
            hedge_exc: Optional[BaseException] = None
            pending = set(owners)
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    target = owners[t]
                    exc = t.exception()
                    if exc is None:
                        result, dt = t.result()
                        winner = "primary" if t is t_primary else "hedge"
                        controller._note_attempt_latency(target, dt)
                        controller._breaker_success(target)
                        if t is t_hedge and not t_primary.done():
                            # the primary is about to be cancelled (or
                            # detached, if probing): not a failure, not
                            # a sample — but the hedge-loss STREAK is
                            # the signal that catches a gray replica
                            # whose own samples hedging dried up
                            controller._note_hedge_loss(primary)
                        self._record_hedge(
                            winner, delay, primary, hedge_replica, method
                        )
                        return result
                    # a GENUINE sub-attempt failure (the loser-cancel
                    # path never reaches here — cancellation happens in
                    # the finally below): transport-classified only,
                    # like every other dispatch path
                    if not is_caller_timeout(exc) and is_retryable(exc):
                        controller._breaker_failure(target, exc)
                    tried.add(target.replica_id)
                    if t is t_primary:
                        primary_exc = exc
                    else:
                        hedge_exc = exc
            # both attempts failed — surface the PRIMARY's error so the
            # outer retry loop classifies exactly what an unhedged
            # attempt would have raised (the hedge replica already sits
            # in `tried` for the next failover pick)
            self._record_hedge(
                "none", delay, primary, hedge_replica, method
            )
            final = primary_exc if primary_exc is not None else hedge_exc
            raise final
        finally:
            if probing and not t_primary.done():
                detached.add(t_primary)
                spawn_supervised(
                    self._settle_probe(t_primary, primary),
                    name=f"hedge-probe-{self.app_id}-{self.deployment}",
                    logger=self._controller.logger,
                )
            live = [
                t
                for t in (t_primary, t_hedge)
                if t is not None and t not in detached
            ]
            for t in live:
                if not t.done():
                    t.cancel()
            # let the cancelled loser unwind its finallys (semaphore
            # slot, ongoing counter, chip accounting) before returning;
            # its CancelledError is swallowed HERE and never fed to the
            # breaker or the outlier EWMA
            if live:
                await asyncio.gather(*live, return_exceptions=True)

    async def _settle_probe(self, task: asyncio.Task, target) -> None:
        """Await a detached probe attempt and bank its evidence: a
        successful completion feeds the outlier EWMA (the probe's whole
        point), a genuine transport failure feeds the breaker, and the
        caller who detached it is long gone either way."""
        controller = self._controller
        try:
            result, dt = await task
        except asyncio.CancelledError:
            return
        except Exception as exc:  # noqa: BLE001 — classified below
            if not is_caller_timeout(exc) and classify_exception(
                exc
            ) is FailureKind.TRANSPORT:
                controller._breaker_failure(target, exc)
            return
        controller._note_attempt_latency(target, dt)

    def _record_hedge(
        self, winner: str, delay: float, primary, hedge_replica, method: str
    ) -> None:
        if metrics.metrics_enabled():
            child = self._m_hedges.get(winner)
            if child is None:
                child = self._m_hedges[winner] = REQUEST_HEDGES.labels(
                    self.app_id, self.deployment, winner
                )
            child.inc()
        flight.record(
            "request.hedge",
            app=self.app_id,
            deployment=self.deployment,
            method=method,
            winner=winner,
            delay_ms=round(delay * 1000.0, 2),
            primary=primary.replica_id,
            hedge=hedge_replica.replica_id,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def invoke(*args, **kwargs):
            return await self.call(name, *args, **kwargs)

        invoke.__name__ = name
        return invoke


def _min_defined(*values: Optional[float]) -> Optional[float]:
    present = [v for v in values if v is not None]
    return min(present) if present else None


class _RouterGate:
    """Admission gate for a standalone router. ``enter()`` refuses
    typed: a CLOSED router (kill/drain) raises
    :class:`RouterClosedError` — retryable, so the client's failover
    loop moves to a sibling router; a SATURATED one raises
    :class:`RouterSaturatedError` — non-retryable backpressure (every
    sibling shares the same replica pool, failing over just moves the
    overload). The in-process controller never builds one."""

    __slots__ = ("router_id", "max_inflight", "inflight", "closed")

    def __init__(self, router_id: str, max_inflight: Optional[int] = None):
        self.router_id = router_id
        self.max_inflight = max_inflight
        self.inflight = 0
        self.closed = False

    def enter(self) -> None:
        if self.closed:
            raise RouterClosedError(
                f"router {self.router_id} is closed to new requests"
            )
        if (
            self.max_inflight is not None
            and self.inflight >= self.max_inflight
        ):
            raise RouterSaturatedError(
                f"router {self.router_id} at its inflight cap "
                f"({self.max_inflight})"
            )
        self.inflight += 1

    def leave(self) -> None:
        if self.inflight > 0:
            self.inflight -= 1


class RouterCore:
    """The request path as a mixin — everything between "a handle was
    called" and "a replica ran it": pick/score, bounded wait, circuit
    breaker, latency-outlier probation, hedging support, scheduler
    attach. ``ServeController`` inherits it (in-process plane,
    bit-compatible attribute names); :class:`StandaloneRouter` inherits
    it over a cached routing table. The host class provides ``apps``
    (``app_id -> AppDeployment``-shaped objects with ``.specs`` and
    ``.replicas``) and ``logger``, then calls :meth:`_init_router_core`
    during its own ``__init__``.

    This is the ONE copy of the routing logic (the satellite-6
    contract): the breaker's caller-timeout exemption lives in
    ``DeploymentHandle`` / here, the scored argmin lives in
    ``scheduler._best_replica`` — neither is duplicated per plane."""

    # standalone routers install a _RouterGate; the controller keeps the
    # class-level None (one attr load + None check on its hot path)
    _router_gate: Optional[_RouterGate] = None

    def _init_router_core(
        self,
        breaker_threshold: Optional[int] = None,
        outlier_config: Optional[OutlierConfig] = None,
    ) -> None:
        # per-replica circuit breaker: K consecutive transport failures
        # eject the replica immediately (no waiting for the health tick)
        self.breaker_threshold = (
            breaker_threshold
            if breaker_threshold is not None
            else int(os.environ.get("BIOENGINE_BREAKER_THRESHOLD", "3"))
        )
        # routable-replica wait during restart windows when the request
        # carries no deadline (read once — this sits on the hot path)
        self.pick_replica_grace_s = float(
            os.environ.get("BIOENGINE_PICK_REPLICA_WAIT_S", "10")
        )
        self._wake_health = asyncio.Event()   # breaker trips ring this
        self._queue_depth: dict[tuple[str, str], int] = defaultdict(int)
        self._rr_counters: dict[tuple[str, str], itertools.count] = {}
        self._breaker_counts: dict[str, int] = {}
        # when each breaker last TRIPPED (monotonic) — a standalone
        # router uses this to hold its local UNHEALTHY verdict against
        # a routing table that still says HEALTHY (the table is the
        # controller's view; the router saw the failures first-hand)
        self._breaker_tripped: dict[str, float] = {}
        # gray-failure defense (serving/outlier.py): per-deployment
        # latency trackers feeding the PROBATION soft-ejection + the
        # p95-derived hedge delay; created lazily on first observation,
        # swept at undeploy like every other router-state dict
        self.outlier_config = outlier_config or OutlierConfig.from_env()
        self._outliers: dict[tuple[str, str], DeploymentLatencyTracker] = {}
        # global schedulers, one per deployment that opted in via
        # DeploymentSpec.scheduling; created at deploy, closed at
        # undeploy. scorer_factory is the pluggable placement policy —
        # swap in a learned scorer without touching the scheduler.
        self._schedulers: dict[tuple[str, str], DeploymentScheduler] = {}
        self.scorer_factory: Callable[[], Any] = HeuristicCostModel
        self._replicas_changed = asyncio.Event()

    # ---- replica pick -------------------------------------------------------

    def get_handle(
        self,
        app_id: str,
        deployment: Optional[str] = None,
        options: Optional[RequestOptions] = None,
    ) -> DeploymentHandle:
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        if deployment is None:
            deployment = next(iter(app.specs))
        if deployment not in app.specs:
            raise KeyError(f"app '{app_id}' has no deployment '{deployment}'")
        self._queue_depth.setdefault((app_id, deployment), 0)
        return DeploymentHandle(self, app_id, deployment, options)

    def _pick_replica(
        self, app_id: str, deployment: str, avoid: Optional[set] = None
    ) -> Replica:
        """Least-loaded routable replica, round-robin tie-break.
        ``avoid`` holds replica_ids that already failed THIS request —
        preferred against, but used as a last resort (the replica may
        have recovered and being wrong just costs one more retry).

        PROBATION replicas (latency outliers, serving/outlier.py) are
        soft-ejected: skipped by the pick except for the trickle probe
        (every Nth pick routes one real request there so recovery is
        observed) — and as the last resort when nothing else is
        routable, because slow beats unavailable."""
        app = self.apps.get(app_id)
        if app is None:
            raise KeyError(f"app '{app_id}' not deployed")
        healthy = [
            r
            for r in app.replicas.get(deployment, [])
            if r.state in ROUTABLE_STATES
        ]
        if avoid:
            preferred = [r for r in healthy if r.replica_id not in avoid]
            healthy = preferred or healthy
        if not healthy:
            raise NoHealthyReplicasError(
                f"no healthy replicas for {app_id}/{deployment}"
            )
        probation = [
            r for r in healthy if r.state == ReplicaState.PROBATION
        ]
        normal = [
            r for r in healthy if r.state != ReplicaState.PROBATION
        ]
        if probation and normal:
            tracker = self._outlier_tracker(app_id, deployment)
            if tracker.take_probe_ticket():
                # the probe trickle: route ONE real request to a
                # probation replica so its latency keeps being measured
                # — recovery is self-correcting, not operator-driven
                healthy = probation
            else:
                healthy = normal
        min_load = min(r.load for r in healthy)
        candidates = [r for r in healthy if r.load == min_load]
        rr = self._rr_counters.setdefault(
            (app_id, deployment), itertools.count()
        )
        return candidates[next(rr) % len(candidates)]

    async def _pick_replica_wait(
        self,
        app_id: str,
        deployment: str,
        avoid: Optional[set] = None,
        deadline: Optional[float] = None,
    ) -> Replica:
        """Like ``_pick_replica`` but WAITS through a restart window
        (bounded by the request deadline, or a default grace period)
        instead of raising instantly — a replica being re-placed after
        a host death is invisible to callers that can afford to wait."""
        wait_until = (
            deadline
            if deadline is not None
            else time.monotonic() + self.pick_replica_grace_s
        )
        while True:
            try:
                return self._pick_replica(app_id, deployment, avoid=avoid)
            except NoHealthyReplicasError:
                gate = self._router_gate
                if gate is not None and gate.closed:
                    # a closed router will never (re-)place a replica —
                    # waiting out the deadline here only burns the
                    # caller's retry budget; refuse typed NOW so the
                    # client fails over to a sibling or a healed plane
                    raise RouterClosedError(
                        f"router {gate.router_id} is closed to new "
                        "requests"
                    ) from None
                remaining = wait_until - time.monotonic()
                if remaining <= 0:
                    raise
                # a waiter with nothing routable is the same signal a
                # breaker trip is: capacity may be back (a rejoined
                # host) with placement still sitting out the health
                # period — ring the health loop so the top-up runs NOW,
                # not up to health_check_period later
                self._wake_health.set()
                self._replicas_changed.clear()
                try:
                    # woken early when a replica is (re-)placed
                    await asyncio.wait_for(
                        self._replicas_changed.wait(), min(remaining, 0.25)
                    )
                except asyncio.TimeoutError:
                    pass

    # ---- circuit breaker ----------------------------------------------------

    def _breaker_failure(self, replica, exc: Exception) -> None:
        """Record one transport failure. At ``breaker_threshold``
        consecutive failures the replica is ejected NOW (marked
        UNHEALTHY + health loop woken) instead of waiting out the
        health period."""
        rid = replica.replica_id
        n = self._breaker_counts.get(rid, 0) + 1
        self._breaker_counts[rid] = n
        if n >= self.breaker_threshold and replica.state in ROUTABLE_STATES:
            replica.state = ReplicaState.UNHEALTHY
            replica.last_error = (
                f"circuit breaker opened after {n} consecutive transport "
                f"failures (last: {exc})"
            )
            self._breaker_tripped[rid] = time.monotonic()
            self.logger.warning(
                f"breaker ejected replica {rid} after {n} transport failures"
            )
            if metrics.metrics_enabled():
                BREAKER_TRIPS.labels(
                    replica.app_id, replica.deployment_name
                ).inc()
            flight.record(
                "breaker.trip",
                severity="error",
                replica=rid,
                app=replica.app_id,
                deployment=replica.deployment_name,
                host=getattr(replica, "host_id", None),
                failures=n,
                error=str(exc)[:500],
            )
            # the postmortem moment: snapshot the ring while the events
            # leading up to the trip are still in it
            flight.dump("breaker_trip", replica=rid, app=replica.app_id)
            self._wake_health.set()

    def _breaker_success(self, replica) -> None:
        if self._breaker_counts.pop(replica.replica_id, None):
            self._breaker_tripped.pop(replica.replica_id, None)
            flight.record(
                "breaker.reset",
                replica=replica.replica_id,
                app=replica.app_id,
                deployment=replica.deployment_name,
            )

    # ---- gray-failure defense (latency outliers → probation) ----------------

    def _outlier_tracker(
        self, app_id: str, deployment: str
    ) -> DeploymentLatencyTracker:
        key = (app_id, deployment)
        tracker = self._outliers.get(key)
        if tracker is None:
            tracker = self._outliers[key] = DeploymentLatencyTracker(
                app_id, deployment, self.outlier_config
            )
        return tracker

    def _note_attempt_latency(self, replica, seconds: float) -> None:
        """Feed one SUCCESSFUL attempt's service time into the
        deployment's outlier tracker and apply the probation verdicts
        it returns (possibly for OTHER replicas of the deployment — a
        hedged-around gray replica stops producing samples of its own,
        so its excursion matures on its siblings' notes). Called by the
        router path, the scheduler's fast path, and group dispatch —
        never for failed attempts (their wall time measures the
        transport) and never for cancelled hedge losers (their wall
        time measures the winner)."""
        tracker = self._outlier_tracker(
            replica.app_id, replica.deployment_name
        )
        transitions = tracker.note(replica.replica_id, seconds)
        self._apply_probation_transitions(tracker, replica, transitions)

    def _note_hedge_loss(self, replica) -> None:
        """A hedge fired against ``replica`` and won. Not a breaker
        strike, not an EWMA sample — but the tracker counts the streak
        (see ``note_hedge_loss``) and may return probation verdicts."""
        tracker = self._outlier_tracker(
            replica.app_id, replica.deployment_name
        )
        transitions = tracker.note_hedge_loss(replica.replica_id)
        self._apply_probation_transitions(tracker, replica, transitions)

    def _apply_probation_transitions(
        self, tracker, replica, transitions
    ) -> None:
        if not transitions:
            return
        app_id = replica.app_id
        deployment = replica.deployment_name
        app = self.apps.get(app_id)
        by_id = {
            r.replica_id: r
            for r in (app.replicas.get(deployment, []) if app else [])
        }
        by_id.setdefault(replica.replica_id, replica)
        median = tracker._median()
        for rid, transition in transitions:
            target = by_id.get(rid)
            if target is None:
                tracker.forget(rid)  # retired mid-flight — stale entry
                continue
            ewma = tracker.ewma(rid)
            # a streak-entered replica may have NO measured EWMA at all
            # (every completion was a cancelled hedge loser) — the
            # evidence attrs must tolerate that, not crash the hedged
            # request that triggered the verdict
            ewma_s = None if ewma is None else round(ewma, 6)
            median_s = None if median is None else round(median, 6)
            if transition == "enter":
                if target.state != ReplicaState.HEALTHY:
                    # TESTING replicas are still warming (compile spikes
                    # are not gray failure) and DRAINING/UNHEALTHY ones
                    # are already out of the pick — roll the verdict back
                    tracker.replicas[rid].in_probation = False
                    continue
                target.state = ReplicaState.PROBATION
                self.logger.warning(
                    f"replica {rid} entered probation: latency EWMA "
                    f"{ewma_s}s vs deployment median {median_s}s "
                    f"(gray failure — health checks still pass)"
                )
                if metrics.metrics_enabled():
                    REPLICA_PROBATIONS.labels(app_id, deployment).inc()
                record_probation_event(
                    app_id, deployment, rid, "enter",
                    ewma_s=ewma_s, median_s=median_s,
                    host=getattr(target, "host_id", None),
                )
            elif transition == "exit":
                if target.state == ReplicaState.PROBATION:
                    target.state = ReplicaState.HEALTHY
                    self._replicas_changed.set()
                self.logger.info(
                    f"replica {rid} recovered from probation "
                    f"(EWMA {ewma_s}s, median {median_s}s)"
                )
                record_probation_event(
                    app_id, deployment, rid, "exit",
                    ewma_s=ewma_s, median_s=median_s,
                    host=getattr(target, "host_id", None),
                )

    def _forget_replica_latency(self, replica_id: str) -> None:
        self._breaker_tripped.pop(replica_id, None)
        for tracker in self._outliers.values():
            tracker.forget(replica_id)

    def hedge_delay_s(
        self, app_id: str, deployment: str, options: "RequestOptions"
    ) -> float:
        if options.hedge_delay_s is not None:
            return options.hedge_delay_s
        return self._outlier_tracker(app_id, deployment).hedge_delay_s()


# ---------------------------------------------------------------------------
# Routing table — publication (controller side)
# ---------------------------------------------------------------------------


class RoutingTablePublisher:
    """Controller-side versioned view of everything a router needs to
    route: the replica set with states and host bindings, per-deployment
    scheduler configs, mesh/host membership, and breaker/probation
    hints. Content-addressed per deployment: ``refresh()`` re-signs each
    deployment's entry list and bumps the monotonic ``version`` only on
    real change, so the diff a router pulls (``since_version``) is
    usually empty. Every table is stamped with the controller's journal
    epoch — the same PR 15 fence hosts use — so a stale controller's
    push can never regress a router (``StaleTableError``).

    Advisory fields (per-entry ``load`` / ``breaker_failures``) are
    deliberately EXCLUDED from the change signature: they churn every
    request, and versioning them would turn every diff into a full
    table. Routers treat them as hints, not truth."""

    def __init__(self, controller):
        self._c = controller
        self.version = 0
        self._dep_version: dict[tuple[str, str], int] = {}
        self._dep_sig: dict[tuple[str, str], Any] = {}
        self._removed_version: dict[tuple[str, str], int] = {}
        self._hosts_sig: Any = None
        self._hosts_version = 0
        # router_id -> last sync report (acked version, staleness, when)
        self.routers: dict[str, dict] = {}

    @staticmethod
    def _entry_sig(r) -> tuple:
        return (
            r.replica_id,
            r.state.value,
            getattr(r, "host_id", None),
            getattr(r, "host_service_id", None),
        )

    def refresh(self) -> int:
        """Re-sign the live placement state; bump ``version`` for each
        deployment whose routable membership changed. O(replicas), no
        allocation on the unchanged path beyond the signatures."""
        c = self._c
        seen: set[tuple[str, str]] = set()
        for app in list(c.apps.values()):
            for dep, replicas in list(app.replicas.items()):
                key = (app.app_id, dep)
                seen.add(key)
                spec = app.specs.get(dep)
                sig = (
                    tuple(self._entry_sig(r) for r in replicas),
                    None if spec is None else (
                        getattr(spec, "max_ongoing_requests", 10),
                        spec.scheduling is not None,
                    ),
                )
                if self._dep_sig.get(key) != sig:
                    self.version += 1
                    self._dep_sig[key] = sig
                    self._dep_version[key] = self.version
                    self._removed_version.pop(key, None)
        for key in [k for k in self._dep_sig if k not in seen]:
            self.version += 1
            del self._dep_sig[key]
            self._dep_version.pop(key, None)
            self._removed_version[key] = self.version
        hosts_sig = tuple(
            sorted(
                (h.host_id, h.service_id, h.alive)
                for h in c.cluster_state.hosts.values()
            )
        )
        if hosts_sig != self._hosts_sig:
            self.version += 1
            self._hosts_sig = hosts_sig
            self._hosts_version = self.version
        return self.version

    def _dep_payload(self, app_id: str, dep: str) -> dict:
        c = self._c
        app = c.apps[app_id]
        spec = app.specs.get(dep)
        entries = []
        for r in app.replicas.get(dep, []):
            entries.append(
                {
                    "replica_id": r.replica_id,
                    "state": r.state.value,
                    "host_id": getattr(r, "host_id", None),
                    "host_service_id": getattr(r, "host_service_id", None),
                    "device_ids": list(getattr(r, "device_ids", []) or []),
                    # advisory hints (NOT versioned — see class docstring)
                    "load": getattr(r, "load", 0),
                    "breaker_failures": c._breaker_counts.get(
                        r.replica_id, 0
                    ),
                }
            )
        sched = spec.scheduling if spec is not None else None
        return {
            "version": self._dep_version[(app_id, dep)],
            "max_ongoing": (
                getattr(spec, "max_ongoing_requests", 10)
                if spec is not None
                else 10
            ),
            "max_replicas": getattr(spec, "max_replicas", 1),
            "target_load": getattr(spec, "target_load", 0.7),
            "scheduling": (
                None
                if sched is None
                else {
                    f: getattr(sched, f)
                    for f in (
                        "enabled", "max_batch", "max_wait_ms",
                        "max_queue_depth", "default_class",
                        "tenant_quota", "target_wait_s",
                        "scale_down_ticks", "ewma_alpha",
                    )
                }
            ),
            "entries": entries,
        }

    def table(
        self,
        since_version: int = 0,
        router_id: Optional[str] = None,
        staleness_s: Optional[float] = None,
    ) -> dict:
        """A full table (``since_version <= 0``) or the diff since a
        version the router already holds. Also books the caller's sync
        report so ``get_app_status`` can surface per-router staleness."""
        self.refresh()
        full = since_version <= 0
        deployments: dict[str, dict] = {}
        for (app_id, dep), ver in self._dep_version.items():
            if full or ver > since_version:
                deployments.setdefault(app_id, {})[dep] = self._dep_payload(
                    app_id, dep
                )
        removed = [
            list(key)
            for key, ver in self._removed_version.items()
            if not full and ver > since_version
        ]
        out = {
            "schema": TABLE_SCHEMA,
            "epoch": self._c.epoch,
            "version": self.version,
            "full": full,
            "generated_at": time.time(),
            "deployments": deployments,
            "removed": removed,
        }
        if full or self._hosts_version > since_version:
            out["hosts"] = {
                h.host_id: {
                    "service_id": h.service_id,
                    "alive": h.alive,
                    "n_chips": h.n_chips,
                }
                for h in self._c.cluster_state.hosts.values()
            }
        if router_id is not None:
            self.note_router(
                router_id,
                acked_version=self.version,
                staleness_s=staleness_s,
            )
        return out

    def note_router(
        self,
        router_id: str,
        acked_version: Optional[int] = None,
        staleness_s: Optional[float] = None,
    ) -> None:
        self.routers[router_id] = {
            "router_id": router_id,
            "acked_version": acked_version,
            "table_epoch": self._c.epoch,
            "staleness_s": (
                None if staleness_s is None else round(staleness_s, 3)
            ),
            "last_sync_at": time.time(),
        }

    def describe(self) -> dict:
        """The ``router_tier`` block of ``get_app_status``."""
        self.refresh()
        return {
            "table_version": self.version,
            "table_epoch": self._c.epoch,
            "routers": [
                self.routers[rid] for rid in sorted(self.routers)
            ],
        }


# ---------------------------------------------------------------------------
# Routing table — consumption (standalone router side)
# ---------------------------------------------------------------------------


@dataclass
class _TableSpec:
    """The slice of ``DeploymentSpec`` a router actually reads,
    reconstructed from a table payload (the full spec carries an
    ``instance_factory`` that cannot cross a process boundary)."""

    name: str
    max_ongoing_requests: int = 10
    max_replicas: int = 1
    target_load: float = 0.7
    scheduling: Optional[SchedulingConfig] = None


@dataclass
class _RouterApp:
    """``AppDeployment``-shaped view a router rebuilds from its table —
    just the fields ``RouterCore`` and the scheduler read."""

    app_id: str
    specs: dict[str, _TableSpec] = field(default_factory=dict)
    replicas: dict[str, list] = field(default_factory=dict)
    status: str = "RUNNING"
    acl: Any = None


def shared_object_resolver(controller) -> Callable:
    """Resolver for routers colocated with the serving plane (the
    scenario engine, in-process scale-out tests): table entries resolve
    to the LIVE replica objects the controller placed, so semaphore
    occupancy, chip accounting, and lifecycle state stay single-source.
    The router therefore never writes replica state from the table
    (``owns_replicas = False``) — the objects already carry it."""

    get = controller if callable(controller) else (lambda: controller)

    def resolve(app_id: str, deployment: str, entries: list) -> list:
        c = get()
        app = c.apps.get(app_id) if c is not None else None
        if app is None:
            return [None] * len(entries)
        by_id = {
            r.replica_id: r for r in app.replicas.get(deployment, [])
        }
        return [by_id.get(e["replica_id"]) for e in entries]

    resolve.owns_replicas = False
    return resolve


def remote_replica_resolver(
    call_host,
    payload: Optional[dict] = None,
    stream_host=None,
) -> Callable:
    """Resolver for a router in its OWN process: each table entry
    becomes a cached :class:`RemoteReplica` dialing the worker host the
    controller placed it on (``call_host`` is the same transport hook
    the controller's remote path uses). The router owns these objects
    (``owns_replicas = True``): lifecycle state is applied FROM the
    table, modulated by the router's local breaker verdicts."""

    cache: dict[tuple[str, str], dict[str, RemoteReplica]] = {}

    def resolve(app_id: str, deployment: str, entries: list) -> list:
        pool = cache.setdefault((app_id, deployment), {})
        out = []
        keep = set()
        for e in entries:
            svc = e.get("host_service_id")
            if not svc:
                # a local (controller-process) replica is unreachable
                # from a remote router — only host-bound entries route
                out.append(None)
                continue
            rid = e["replica_id"]
            keep.add(rid)
            replica = pool.get(rid)
            if replica is None:
                replica = RemoteReplica(
                    app_id,
                    deployment,
                    e.get("host_id"),
                    svc,
                    call_host,
                    dict(payload or {}),
                    device_ids=list(e.get("device_ids") or []),
                    max_ongoing_requests=int(e.get("max_ongoing", 10)),
                    stream_host=stream_host,
                )
                replica.replica_id = rid
                pool[rid] = replica
            out.append(replica)
        for rid in [r for r in pool if r not in keep]:
            del pool[rid]
        return out

    resolve.owns_replicas = True
    return resolve


class StandaloneRouter(RouterCore):
    """A scale-out router: the full ``RouterCore`` request path over a
    locally cached routing table instead of live placement state. N of
    these serve concurrently against one controller; each keeps serving
    its last-good table through a controller crash/restart and reports
    the table's staleness age.

    ``resolver`` turns table entries into callable replica objects —
    :func:`shared_object_resolver` for a colocated router (scenario
    engine), :func:`remote_replica_resolver` for a router process
    dialing worker hosts over RPC.

    Table application is epoch-fenced (:meth:`apply_table`); syncing is
    the caller's loop — :meth:`sync_from` against an in-process
    controller, :meth:`sync_once` over the RPC plane, or
    :meth:`sync_loop` to run either on a period
    (``BIOENGINE_ROUTER_SYNC_S``)."""

    def __init__(
        self,
        router_id: Optional[str] = None,
        resolver: Optional[Callable] = None,
        *,
        breaker_threshold: Optional[int] = None,
        outlier_config: Optional[OutlierConfig] = None,
        max_inflight: Optional[int] = None,
        table_stale_s: Optional[float] = None,
        log_file: Optional[str] = None,
    ):
        self.router_id = router_id or f"router-{os.getpid()}-{id(self):x}"
        self.apps: dict[str, _RouterApp] = {}
        self.logger = create_logger(
            f"router.{self.router_id}", log_file=log_file
        )
        self._init_router_core(
            breaker_threshold=breaker_threshold,
            outlier_config=outlier_config,
        )
        if max_inflight is None:
            raw = os.environ.get("BIOENGINE_ROUTER_MAX_INFLIGHT", "")
            max_inflight = int(raw) if raw else None
        self._router_gate = _RouterGate(self.router_id, max_inflight)
        # staleness past this bound flags the router DEGRADED in
        # describe() — it still serves (last-good beats nothing), the
        # flag is the operator signal
        self.table_stale_s = (
            table_stale_s
            if table_stale_s is not None
            else float(os.environ.get("BIOENGINE_ROUTER_TABLE_STALE_S", "30"))
        )
        # how long a local breaker verdict outranks a table that still
        # says HEALTHY (the router saw the failures first-hand; the
        # controller's view lags a health tick)
        self.breaker_hold_s = float(
            os.environ.get("BIOENGINE_ROUTER_BREAKER_HOLD_S", "30")
        )
        self._resolver = resolver or (
            lambda app_id, dep, entries: [None] * len(entries)
        )
        self.table_epoch = 0
        self.table_version = 0
        # staleness baseline: construction counts as "last applied", so
        # a router that never synced reports its age, not infinity
        self._table_applied_mono = time.monotonic()
        self._table_generated_at: Optional[float] = None
        self.hosts: dict[str, dict] = {}
        _ROUTERS.add(self)

    # ---- table lifecycle ----------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._router_gate.closed

    @property
    def table_staleness_s(self) -> float:
        """Seconds since a routing table was last applied."""
        return max(0.0, time.monotonic() - self._table_applied_mono)

    def apply_table(self, table: dict) -> dict:
        """Apply a published table (full or diff). Fencing, in order:

        - LOWER epoch than held → the publisher is a revived old
          controller; rejected typed (``StaleTableError``), view kept.
        - Same epoch, LOWER version → reordered/duplicate push;
          rejected typed, view kept (a stale push never regresses).
        - HIGHER epoch → new controller generation; only a FULL table
          is acceptable (the old generation's version stream means
          nothing), and it resets the view.
        """
        epoch = int(table.get("epoch", 0))
        version = int(table.get("version", 0))
        full = bool(table.get("full", False))
        if epoch < self.table_epoch or (
            epoch == self.table_epoch and version < self.table_version
        ):
            reason = (
                "stale_epoch" if epoch < self.table_epoch else "stale_version"
            )
            flight.record(
                "router.table_reject",
                severity="warning",
                router=self.router_id,
                reason=reason,
                held_epoch=self.table_epoch,
                held_version=self.table_version,
                got_epoch=epoch,
                got_version=version,
            )
            raise StaleTableError(
                f"router {self.router_id} holds table "
                f"epoch={self.table_epoch} version={self.table_version}; "
                f"rejecting {reason} push "
                f"(epoch={epoch} version={version})",
                seen_epoch=self.table_epoch,
                got_epoch=epoch,
            )
        if epoch > self.table_epoch and self.table_epoch > 0 and not full:
            flight.record(
                "router.table_reject",
                severity="warning",
                router=self.router_id,
                reason="diff_across_epochs",
                held_epoch=self.table_epoch,
                got_epoch=epoch,
            )
            raise ValueError(
                f"router {self.router_id}: a diff cannot cross a controller "
                f"generation (held epoch {self.table_epoch}, got {epoch}) — "
                f"resync with since_version=0"
            )
        if epoch == self.table_epoch and version == self.table_version:
            # no-op push, but a live publisher just CONFIRMED the held
            # view is current — that resets the staleness clock (else a
            # quiet fleet would read as ever-more-stale between changes)
            self._table_applied_mono = time.monotonic()
            return {"applied": False, "reason": "duplicate",
                    "epoch": epoch, "version": version}

        deployments = table.get("deployments") or {}
        applied = 0
        for app_id, deps in deployments.items():
            for dep, payload in deps.items():
                self._apply_deployment(app_id, dep, payload)
                applied += 1
        removed = [tuple(k) for k in (table.get("removed") or [])]
        for app_id, dep in removed:
            self._remove_deployment(app_id, dep)
        if full:
            # a full table is authoritative: prune deployments it no
            # longer lists (covers removals that predate this router)
            listed = {
                (app_id, dep)
                for app_id, deps in deployments.items()
                for dep in deps
            }
            for app in list(self.apps.values()):
                for dep in list(app.specs):
                    if (app.app_id, dep) not in listed:
                        self._remove_deployment(app.app_id, dep)
        if "hosts" in table:
            self.hosts = dict(table["hosts"] or {})
        self.table_epoch = epoch
        self.table_version = version
        self._table_applied_mono = time.monotonic()
        self._table_generated_at = table.get("generated_at")
        self._replicas_changed.set()
        flight.record(
            "router.table_apply",
            router=self.router_id,
            epoch=epoch,
            version=version,
            full=full,
            deployments=applied,
            removed=len(removed),
        )
        return {"applied": True, "epoch": epoch, "version": version,
                "deployments": applied, "removed": len(removed)}

    def _apply_deployment(
        self, app_id: str, dep: str, payload: dict
    ) -> None:
        app = self.apps.get(app_id)
        if app is None:
            app = self.apps[app_id] = _RouterApp(app_id=app_id)
        entries = payload.get("entries") or []
        resolved = self._resolver(app_id, dep, entries)
        owned = getattr(self._resolver, "owns_replicas", False)
        live = []
        for entry, replica in zip(entries, resolved):
            if replica is None:
                continue
            if owned:
                desired = ReplicaState(entry["state"])
                rid = entry["replica_id"]
                # the table says routable but the LOCAL breaker tripped
                # recently: the router saw those failures first-hand and
                # holds its verdict for breaker_hold_s (the controller's
                # view lags a health tick)
                veto = (
                    replica.state is ReplicaState.UNHEALTHY
                    and desired in ROUTABLE_STATES
                    and self._breaker_counts.get(rid, 0)
                    >= self.breaker_threshold
                    and (
                        time.monotonic()
                        - self._breaker_tripped.get(rid, 0.0)
                    )
                    < self.breaker_hold_s
                )
                if not veto:
                    replica.state = desired
            live.append(replica)
        app.replicas[dep] = live
        sched_cfg = payload.get("scheduling")
        spec = _TableSpec(
            name=dep,
            max_ongoing_requests=int(payload.get("max_ongoing", 10)),
            max_replicas=int(payload.get("max_replicas", 1)),
            target_load=float(payload.get("target_load", 0.7)),
        )
        app.specs[dep] = spec
        self._queue_depth.setdefault((app_id, dep), 0)
        key = (app_id, dep)
        if sched_cfg:
            cfg = SchedulingConfig.from_config(dict(sched_cfg))
            spec.scheduling = cfg
            if key not in self._schedulers:
                self._schedulers[key] = DeploymentScheduler(
                    self, app_id, dep, spec, cfg,
                    scorer=self.scorer_factory(),
                )
        elif key in self._schedulers:
            self._schedulers.pop(key).kill()

    def _remove_deployment(self, app_id: str, dep: str) -> None:
        key = (app_id, dep)
        sched = self._schedulers.pop(key, None)
        if sched is not None:
            sched.kill()
        self._rr_counters.pop(key, None)
        self._outliers.pop(key, None)
        self._queue_depth.pop(key, None)
        app = self.apps.get(app_id)
        if app is not None:
            app.replicas.pop(dep, None)
            app.specs.pop(dep, None)
            if not app.specs:
                self.apps.pop(app_id, None)

    # ---- sync ---------------------------------------------------------------

    def _since_version(self, publisher_epoch: int) -> int:
        # a diff is only meaningful within one controller generation
        return self.table_version if publisher_epoch == self.table_epoch else 0

    def sync_from(self, controller) -> dict:
        """One in-process sync against a live controller's publisher
        (colocated deployments, the scenario engine)."""
        table = controller.router_publisher.table(
            since_version=self._since_version(controller.epoch),
            router_id=self.router_id,
            staleness_s=self.table_staleness_s,
        )
        return self.apply_table(table)

    async def sync_once(self, controller_service) -> dict:
        """One sync over the RPC plane: ``controller_service`` is a
        connected client for the controller's ``serve-router`` service
        (the same wrapper worker hosts hold)."""
        table = await controller_service.call_service_method(
            "serve-router",
            "get_routing_table",
            self.router_id,
            self._since_version(self.table_epoch),
            self.table_staleness_s,
        )
        return self.apply_table(table)

    async def sync_loop(
        self, source, period_s: Optional[float] = None
    ) -> None:
        """Periodic sync until the router is killed. ``source`` is a
        live controller (in-process) or an RPC service client. Sync
        failures degrade staleness, never the router — it keeps serving
        the last-good table (that is the whole point of the cache)."""
        if period_s is None:
            period_s = float(os.environ.get("BIOENGINE_ROUTER_SYNC_S", "2"))
        is_local = hasattr(source, "router_publisher")
        while not self.closed:
            try:
                if is_local:
                    self.sync_from(source)
                else:
                    await self.sync_once(source)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — staleness IS the signal
                self.logger.warning(
                    f"router {self.router_id} table sync failed "
                    f"(serving last-good, "
                    f"staleness={self.table_staleness_s:.1f}s): {e}"
                )
            await asyncio.sleep(period_s)

    # ---- lifecycle ----------------------------------------------------------

    def kill(self) -> None:
        """Stop admitting requests (in-flight ones finish). New calls
        get ``RouterClosedError`` — retryable, so clients fail over to
        a sibling router."""
        if self._router_gate.closed:
            return
        self._router_gate.closed = True
        for sched in list(self._schedulers.values()):
            sched.kill()
        self._schedulers.clear()
        flight.record(
            "router.closed",
            router=self.router_id,
            table_epoch=self.table_epoch,
            table_version=self.table_version,
            inflight=self._router_gate.inflight,
        )

    def describe(self) -> dict:
        staleness = self.table_staleness_s
        return {
            "router_id": self.router_id,
            "closed": self.closed,
            "table_epoch": self.table_epoch,
            "table_version": self.table_version,
            "table_staleness_s": round(staleness, 3),
            "stale": staleness > self.table_stale_s,
            "inflight": self._router_gate.inflight,
            "max_inflight": self._router_gate.max_inflight,
            "deployments": sorted(
                f"{app.app_id}/{dep}"
                for app in self.apps.values()
                for dep in app.specs
            ),
            "hosts": len(self.hosts),
        }


def _collect_routers(instances: list) -> list:
    """Scrape-time gauges from live standalone routers: the table
    epoch/staleness pair is the split-brain + liveness signal the
    fleet dashboard alerts on (a router serving a stale table keeps
    serving — the alert is the operator's cue, not a failure)."""
    out = []
    for r in instances:
        labels = {"router": r.router_id}
        out.append(
            metrics.Sample(
                "router_table_epoch",
                r.table_epoch,
                labels,
                help="journal epoch of the router's applied routing table",
            )
        )
        out.append(
            metrics.Sample(
                "router_table_staleness_seconds",
                round(r.table_staleness_s, 3),
                labels,
                help="seconds since the router last applied a routing table",
            )
        )
        out.append(
            metrics.Sample(
                "router_inflight_requests",
                r._router_gate.inflight,
                labels,
                help="requests currently admitted by the router's gate",
            )
        )
    return out


_ROUTERS = metrics.InstanceSet("standalone_router", _collect_routers)
