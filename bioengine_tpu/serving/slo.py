"""SLO engine — error-budget burn-rate alerting + anomaly detection.

The telemetry store (utils/telemetry.py) remembers what every
deployment did; this module decides whether that history is *meeting
the deployment's service objectives* and raises a hand BEFORE an
operator eyeballs a dashboard:

- **Objectives** come from the manifest's per-deployment ``slo:``
  block (:class:`SLOConfig`): a latency objective at a percentile
  ("99% of requests under 250 ms") and/or an availability target
  ("99.9% of requests succeed"), over a rolling window. Both reduce to
  the same good/bad-event arithmetic: the error budget is
  ``1 - target``, and the burn rate over a window is
  ``bad_fraction / budget`` (burn 1.0 = spending the budget exactly at
  the rate that exhausts it at the window's end).
- **Multi-window multi-burn-rate rules** (Google SRE workbook ch.5):
  an alert fires only when BOTH a long window (sustained) and a short
  window (still happening) exceed the severity's burn threshold —
  fast burns page in minutes, slow burns ticket in hours, and a
  recovered incident stops alerting as soon as the short window goes
  quiet. Rule windows are fractions of the SLO window, floored to the
  store's base resolution so second-scale test windows work.
- **An alert state machine** per (deployment, objective):
  ``inactive -> pending -> firing -> resolved``. Transitions land in
  the flight ring (``slo.pending`` / ``slo.firing`` / ``slo.resolved``),
  firing increments ``slo_alerts_total{app,deployment,severity}``, and
  a page-severity firing invokes the controller's auto-bundle hook —
  rate-limited — so the incident artifact exists before anyone is
  paged.
- **Anomaly detection** for what SLOs don't cover: EWMA+variance
  residual detectors over the stored base-resolution series
  (latency p99, error ratio, queue depth, request rate) flag
  excursions as ``anomaly.detect`` warn events.
- **Closing the loop**: :meth:`SLOEngine.burn_pressure` exposes the
  current worst short-window burn (normalized to the page threshold)
  as a scalar the scheduler's predictive autoscaler can consume
  (``scheduling.slo_pressure: true`` — off by default): a deployment
  burning its budget scales up even when queue projections alone say
  hold.
"""

from __future__ import annotations

import math
import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from bioengine_tpu.utils import flight, metrics
from bioengine_tpu.utils.telemetry import TelemetryStore, quantile_from_buckets

SLO_ALERTS = metrics.counter(
    "slo_alerts_total",
    "SLO alerts that reached firing, by severity",
    ("app", "deployment", "severity"),
)
ANOMALIES = metrics.counter(
    "anomalies_total",
    "series excursions flagged by the residual detectors",
    ("app", "deployment", "series"),
)

# (severity, burn threshold, long window fraction, short window fraction)
# of the SLO window — for the canonical 30d window these are the SRE
# workbook's 14.4x over 1h&5m page and 6x over 6h&30m ticket, scaled.
BURN_RULES: tuple[tuple[str, float, float, float], ...] = (
    ("page", 14.4, 1.0 / 720.0, 1.0 / 8640.0),
    ("ticket", 6.0, 1.0 / 120.0, 1.0 / 1440.0),
)

# a resolved alert reads "resolved" for this long, then quietly decays
# to inactive — status surfaces must distinguish "recently recovered"
# from "incident badge worn forever"
RESOLVED_DECAY_S = 3600.0

_DURATION_RE = re.compile(r"^\s*([0-9.]+)\s*(ms|s|m|h|d)?\s*$")
_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration_s(value: Any, default_unit: str = "s") -> float:
    """``"250ms" | "1h" | "30d" | 60 | "60"`` -> seconds."""
    if isinstance(value, (int, float)):
        return float(value) * _DURATION_UNITS[default_unit]
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"unparseable duration: {value!r}")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2) or default_unit]


SLO_KEYS = {
    "latency_objective_ms",
    "latency_objective",
    "latency_percentile",
    "availability",
    "inter_token_ms",
    "window",
    "for",
}


@dataclass(frozen=True)
class SLOConfig:
    """One deployment's service objectives (manifest:
    ``deployment_config.<dep>.slo``)."""

    latency_objective_s: Optional[float] = None
    latency_percentile: float = 99.0       # % of requests under objective
    availability: Optional[float] = None   # % of requests that succeed
    # token-streaming deployments: % (latency_percentile) of
    # inter-token gaps under this bound — the generative-serving SLO
    # (time BETWEEN tokens at the caller edge; burn-rate rules apply
    # to it exactly as to request latency)
    inter_token_objective_s: Optional[float] = None
    window_s: float = 30 * 86400.0
    for_s: float = 0.0                     # pending hold before firing

    @classmethod
    def from_config(cls, cfg: dict) -> "SLOConfig":
        unknown = sorted(set(cfg) - SLO_KEYS)
        if unknown:
            raise ValueError(
                f"unknown slo keys: {unknown} (accepted: {sorted(SLO_KEYS)})"
            )
        latency = None
        if "latency_objective_ms" in cfg:
            latency = float(cfg["latency_objective_ms"]) / 1000.0
        elif "latency_objective" in cfg:
            latency = parse_duration_s(cfg["latency_objective"])
        availability = (
            float(cfg["availability"]) if "availability" in cfg else None
        )
        inter_token = (
            float(cfg["inter_token_ms"]) / 1000.0
            if "inter_token_ms" in cfg
            else None
        )
        if inter_token is not None and inter_token <= 0:
            raise ValueError("inter_token_ms must be positive")
        if latency is None and availability is None and inter_token is None:
            raise ValueError(
                "slo block needs latency_objective_ms, availability, "
                "and/or inter_token_ms"
            )
        pct = float(cfg.get("latency_percentile", 99.0))
        # floor at 50: values below are either nonsense objectives or —
        # the common foot-gun — FRACTIONS (0.999 meaning 99.9%), which
        # would pass a (0,100) check and produce an SLO that can never
        # alert. Fail the build, not the incident.
        if not 50.0 <= pct < 100.0:
            raise ValueError(
                f"latency_percentile must be in [50, 100) percent, got "
                f"{pct} (use 99.9, not 0.999)"
            )
        if availability is not None and not 50.0 <= availability < 100.0:
            raise ValueError(
                f"availability must be in [50, 100) percent, got "
                f"{availability} (use 99.9, not 0.999)"
            )
        window = parse_duration_s(cfg.get("window", 30 * 86400.0))
        if window <= 0:
            raise ValueError("slo window must be positive")
        return cls(
            latency_objective_s=latency,
            latency_percentile=pct,
            availability=availability,
            inter_token_objective_s=inter_token,
            window_s=window,
            for_s=parse_duration_s(cfg.get("for", 0.0)),
        )

    def objectives(self) -> list[str]:
        out = []
        if self.latency_objective_s is not None:
            out.append("latency")
        if self.availability is not None:
            out.append("availability")
        if self.inter_token_objective_s is not None:
            out.append("inter_token")
        return out

    def budget(self, objective: str) -> float:
        if objective in ("latency", "inter_token"):
            return max(1e-6, 1.0 - self.latency_percentile / 100.0)
        return max(1e-6, 1.0 - (self.availability or 100.0) / 100.0)


# ---------------------------------------------------------------------------
# anomaly detection
# ---------------------------------------------------------------------------


class ResidualDetector:
    """EWMA mean + EW variance over one series; a point whose residual
    z-score exceeds ``z`` for ``consecutive`` points is an excursion.
    The mean/variance update is SKIPPED while a streak is building (a
    step change must not teach the detector it is normal before being
    flagged), but the FLAGGING point does update — the inflated
    variance then absorbs a sustained level shift after one event
    instead of re-flagging it forever."""

    def __init__(
        self,
        alpha: float = 0.3,
        z: float = 4.0,
        min_points: int = 8,
        consecutive: int = 2,
        min_delta: float = 0.0,
    ):
        self.alpha = alpha
        self.z = z
        self.min_points = min_points
        self.consecutive = consecutive
        self.min_delta = min_delta     # absolute floor: tiny wiggles never flag
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self._streak = 0

    def observe(self, value: float) -> bool:
        if self.n < self.min_points:
            # warmup: learn the baseline before judging anything
            self._update(value)
            return False
        std = math.sqrt(max(self.var, 1e-12))
        resid = abs(value - self.mean)
        if resid > self.z * std and resid > self.min_delta:
            self._streak += 1
            if self._streak >= self.consecutive:
                self._streak = 0
                # learn from the flagged point: the EW variance blows
                # up with d^2, so a persistent new level stops flagging
                # after ~one event and the baseline re-converges
                self._update(value)
                return True
            return False
        self._streak = 0
        self._update(value)
        return False

    def _update(self, value: float) -> None:
        self.n += 1
        if self.n == 1:
            self.mean = value
            return
        d = value - self.mean
        self.mean += self.alpha * d
        self.var = (1 - self.alpha) * (self.var + self.alpha * d * d)


# series the anomaly pass watches, with absolute floors so idle-noise
# never pages anyone (an error_ratio wiggle of 0.3% or one queued
# request is not an incident)
ANOMALY_SERIES: tuple[tuple[str, float], ...] = (
    ("latency_p99", 0.010),
    ("error_ratio", 0.02),
    ("queue_depth", 2.0),
    ("request_rate", 1.0),
)


# ---------------------------------------------------------------------------
# alert state machine
# ---------------------------------------------------------------------------


@dataclass
class AlertState:
    objective: str                    # "latency" | "availability"
    state: str = "inactive"           # inactive|pending|firing|resolved
    severity: Optional[str] = None
    since: Optional[float] = None     # wall clock of entering pending/firing
    last_transition: Optional[float] = None
    burn_long: float = 0.0
    burn_short: float = 0.0
    windows: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "objective": self.objective,
            "state": self.state,
            "severity": self.severity,
            "since": self.since,
            "last_transition": self.last_transition,
            "burn_long": round(self.burn_long, 3),
            "burn_short": round(self.burn_short, 3),
            "windows": dict(self.windows),
        }


class SLOEngine:
    """Evaluates every registered deployment's objectives against the
    telemetry store. Owned by the controller; ``evaluate()`` runs on
    the telemetry tick (no hot-path cost whatsoever)."""

    def __init__(
        self,
        store: TelemetryStore,
        on_page: Optional[Callable[[dict], Any]] = None,
        logger=None,
    ):
        self.store = store
        self.on_page = on_page         # controller's auto-bundle hook
        self.logger = logger
        self._slos: dict[tuple[str, str], SLOConfig] = {}
        self._alerts: dict[tuple[str, str, str], AlertState] = {}
        self._detectors: dict[tuple, ResidualDetector] = {}
        self._detector_cursor: dict[tuple, float] = {}
        self._recent_anomalies: deque = deque(maxlen=64)
        self._base_step = min(s for s, _ in store.resolutions)

    # ---- registration (deploy/undeploy) -------------------------------------

    def register(self, app: str, deployment: str, cfg: SLOConfig) -> None:
        self._slos[(app, deployment)] = cfg

    def unregister(self, app: str, deployment: Optional[str] = None) -> None:
        for key in [
            k
            for k in self._slos
            if k[0] == app and (deployment is None or k[1] == deployment)
        ]:
            del self._slos[key]
        for key in [
            k
            for k in self._alerts
            if k[0] == app and (deployment is None or k[1] == deployment)
        ]:
            del self._alerts[key]
        for key in [
            k
            for k in self._detectors
            if k[0] == app and (deployment is None or k[1] == deployment)
        ]:
            self._detectors.pop(key, None)
            self._detector_cursor.pop(key, None)

    def deployments(self) -> list[tuple[str, str]]:
        return sorted(self._slos)

    # ---- burn math ----------------------------------------------------------

    def _bad_fraction(
        self, app: str, dep: str, cfg: SLOConfig, objective: str, window_s: float, now: float
    ) -> tuple[Optional[float], float]:
        """(bad fraction over the window, total requests). None when
        the window holds no traffic — no traffic is not an outage."""
        agg = self.store.window_aggregate(app, dep, window_s, now=now)
        if objective == "inter_token":
            # the event is one inter-token GAP, not one request: the
            # budget burns against the gap-histogram count, so a single
            # stalled long generation burns proportionally to its stall
            total = agg.get("inter_token_count", 0.0)
            if total <= 0:
                return None, 0.0
            buckets = agg.get("inter_token_buckets", {})
            good = 0.0
            for edge_str, cum in buckets.items():
                edge = math.inf if edge_str == "+Inf" else float(edge_str)
                if edge <= cfg.inter_token_objective_s + 1e-9:
                    good = max(good, cum)
            return min(1.0, max(0.0, total - good) / total), total
        total = agg.get("requests", 0.0)
        if total <= 0:
            return None, 0.0
        if objective == "availability":
            return min(1.0, agg.get("errors", 0.0) / total), total
        # latency: good = finished under the objective. Stored bucket
        # deltas are ZERO-SUPPRESSED cumulative counts (an edge absent
        # from a delta saw no change), so count_le(objective) is the
        # count at the LARGEST present edge <= the objective — any
        # absent edge in between contributed zero. Bucket edges
        # quantize the objective conservatively: an objective between
        # edges counts the span up to the next edge as bad (align the
        # objective with a bucket edge — docs/observability.md).
        buckets = agg.get("latency_buckets", {})
        good = 0.0
        for edge_str, cum in buckets.items():
            edge = math.inf if edge_str == "+Inf" else float(edge_str)
            if edge <= cfg.latency_objective_s + 1e-9:
                good = max(good, cum)
        bad = max(0.0, total - good)
        return min(1.0, bad / total), total

    def _rule_windows(self, cfg: SLOConfig) -> list[tuple[str, float, float, float]]:
        out = []
        for severity, threshold, long_f, short_f in BURN_RULES:
            long_w = max(cfg.window_s * long_f, self._base_step)
            short_w = max(cfg.window_s * short_f, self._base_step)
            out.append((severity, threshold, long_w, short_w))
        return out

    def _evaluate_objective(
        self, app: str, dep: str, cfg: SLOConfig, objective: str, now: float
    ) -> AlertState:
        key = (app, dep, objective)
        alert = self._alerts.get(key)
        if alert is None:
            alert = self._alerts[key] = AlertState(objective=objective)
        budget = cfg.budget(objective)
        condition = None    # (severity, burn_long, burn_short, windows)
        burns = {}
        for severity, threshold, long_w, short_w in self._rule_windows(cfg):
            frac_long, _ = self._bad_fraction(app, dep, cfg, objective, long_w, now)
            frac_short, _ = self._bad_fraction(app, dep, cfg, objective, short_w, now)
            burn_long = (frac_long or 0.0) / budget
            burn_short = (frac_short or 0.0) / budget
            burns[severity] = {
                "burn_long": round(burn_long, 3),
                "burn_short": round(burn_short, 3),
                "threshold": threshold,
                "long_window_s": round(long_w, 3),
                "short_window_s": round(short_w, 3),
            }
            if (
                condition is None
                and frac_long is not None
                and burn_long >= threshold
                and burn_short >= threshold
            ):
                condition = (severity, burn_long, burn_short, {
                    "long_s": round(long_w, 3), "short_s": round(short_w, 3),
                })
        alert.windows = burns
        if condition is not None:
            severity, burn_long, burn_short, windows = condition
            alert.burn_long, alert.burn_short = burn_long, burn_short
            if alert.state in ("inactive", "resolved"):
                self._transition(app, dep, alert, "pending", severity, now)
            elif alert.state == "pending":
                if now - (alert.since or now) >= cfg.for_s:
                    self._transition(app, dep, alert, "firing", severity, now)
            elif alert.state == "firing" and severity != alert.severity:
                if severity == "page":
                    # ESCALATION to page while already firing (the
                    # slow-then-fast burn): a page is a new alert —
                    # re-fire so the counter, flight event, and
                    # auto-bundle hook all run
                    self._transition(app, dep, alert, "firing", severity, now)
                else:
                    # de-escalation: stay firing, record the new class
                    alert.severity = severity
        else:
            alert.burn_long = max(
                (b["burn_long"] for b in burns.values()), default=0.0
            )
            alert.burn_short = max(
                (b["burn_short"] for b in burns.values()), default=0.0
            )
            if alert.state in ("pending", "firing"):
                self._transition(app, dep, alert, "resolved", alert.severity, now)
            elif (
                alert.state == "resolved"
                and alert.last_transition is not None
                and now - alert.last_transition >= RESOLVED_DECAY_S
            ):
                # quiet decay (no flight event): after an hour of calm
                # the deployment reads "ok" again instead of wearing
                # last week's incident forever
                alert.state = "inactive"
                alert.severity = None
        return alert

    def _transition(
        self,
        app: str,
        dep: str,
        alert: AlertState,
        state: str,
        severity: Optional[str],
        now: float,
    ) -> None:
        prev = alert.state
        alert.state = state
        alert.severity = severity
        alert.last_transition = now
        if state == "pending":
            alert.since = now
        attrs = {
            "app": app,
            "deployment": dep,
            "objective": alert.objective,
            # "severity" is the flight event's own level — the alert's
            # page/ticket class rides as alert_severity
            "alert_severity": severity,
            "from": prev,
            "burn_long": round(alert.burn_long, 3),
            "burn_short": round(alert.burn_short, 3),
        }
        flight.record(
            f"slo.{state}",
            severity=(
                "error" if state == "firing" and severity == "page"
                else "warning" if state in ("pending", "firing")
                else "info"
            ),
            **attrs,
        )
        if self.logger is not None:
            self.logger.warning(
                f"slo_alert app={app} deployment={dep} "
                f"objective={alert.objective} state={prev}->{state} "
                f"severity={severity} burn_long={alert.burn_long:.2f} "
                f"burn_short={alert.burn_short:.2f}"
            )
        if state == "firing":
            SLO_ALERTS.labels(app, dep, severity or "none").inc()
            if severity == "page" and self.on_page is not None:
                try:
                    self.on_page(
                        {"app": app, "deployment": dep, **alert.as_dict()}
                    )
                except Exception as e:  # noqa: BLE001 — bundling never breaks eval
                    if self.logger is not None:
                        self.logger.error(f"slo on_page hook failed: {e}")

    # ---- anomaly pass -------------------------------------------------------

    def _anomaly_pass(self, app: str, dep: str, now: float) -> None:
        for series_name, min_delta in ANOMALY_SERIES:
            key = (app, dep, series_name)
            det = self._detectors.get(key)
            if det is None:
                det = self._detectors[key] = ResidualDetector(
                    min_delta=min_delta
                )
            cursor = self._detector_cursor.get(key, 0.0)
            points = self.store.series(
                app, dep, series_name,
                since=cursor or None,
                resolution=self._base_step,
                now=now,
            )
            for p in points:
                # never judge the still-open newest bucket — it holds a
                # partial interval and would alias as a rate dip
                if p["t"] + self._base_step > now:
                    continue
                if p["t"] <= cursor:
                    continue
                self._detector_cursor[key] = p["t"]
                v = p["value"]
                if v is None or not math.isfinite(v):
                    continue
                if det.observe(v):
                    ANOMALIES.labels(app, dep, series_name).inc()
                    evt = {
                        "app": app,
                        "deployment": dep,
                        "series": series_name,
                        "value": round(v, 6),
                        "expected": round(det.mean, 6),
                        "sigma": round(math.sqrt(max(det.var, 0.0)), 6),
                        "t": p["t"],
                    }
                    self._recent_anomalies.append({**evt, "detected_at": now})
                    flight.record(
                        "anomaly.detect", severity="warning", **evt
                    )

    # ---- the tick -----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass over every registered deployment.
        Returns the same JSON-able status dict ``get_slo_status``
        serves."""
        now = now if now is not None else time.time()
        for (app, dep), cfg in list(self._slos.items()):
            for objective in cfg.objectives():
                self._evaluate_objective(app, dep, cfg, objective, now)
            self._anomaly_pass(app, dep, now)
        return self.status(now=now)

    def burn_pressure(self, app: str, deployment: str) -> float:
        """Worst current short-window burn across this deployment's
        objectives, normalized to the page threshold (>= 1.0 means
        page-rate budget burn). The scheduler's predictive autoscaler
        consumes this when ``scheduling.slo_pressure`` is on."""
        page_threshold = BURN_RULES[0][1]
        worst = 0.0
        for (a, d, _obj), alert in self._alerts.items():
            if a == app and d == deployment:
                worst = max(worst, alert.burn_short / page_threshold)
        return worst

    def status(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.time()
        out: dict[str, Any] = {"generated_at": now, "deployments": {}}
        coverage = self.store.coverage_s()
        for (app, dep), cfg in sorted(self._slos.items()):
            objectives = {}
            for objective in cfg.objectives():
                alert = self._alerts.get((app, dep, objective))
                # honesty over a long SLO window: the store holds at
                # most ``coverage`` of history, so full-window budget
                # math is computed (and LABELED) over the covered span
                # — a 30d objective on the default 24h store reports
                # window_truncated rather than a silently-24h number
                effective_window = min(cfg.window_s, coverage)
                frac, total = self._bad_fraction(
                    app, dep, cfg, objective, effective_window, now
                )
                budget = cfg.budget(objective)
                objectives[objective] = {
                    "target": (
                        cfg.availability
                        if objective == "availability"
                        else cfg.latency_percentile
                    ),
                    "latency_objective_ms": (
                        round(cfg.latency_objective_s * 1000.0, 3)
                        if objective == "latency"
                        else None
                    ),
                    "inter_token_objective_ms": (
                        round(cfg.inter_token_objective_s * 1000.0, 3)
                        if objective == "inter_token"
                        else None
                    ),
                    "window_s": cfg.window_s,
                    "window_coverage_s": effective_window,
                    "window_truncated": coverage < cfg.window_s,
                    "requests_in_window": total,
                    "bad_fraction": (
                        round(frac, 6) if frac is not None else None
                    ),
                    "budget_remaining": (
                        round(1.0 - frac / budget, 4)
                        if frac is not None
                        else 1.0
                    ),
                    "alert": alert.as_dict() if alert else None,
                }
            out["deployments"][f"{app}/{dep}"] = {
                "objectives": objectives,
                "burn_pressure": round(self.burn_pressure(app, dep), 3),
            }
        out["anomalies"] = list(self._recent_anomalies)[-16:]
        return out
