"""Cross-host mesh execution — one logical replica over several hosts.

:class:`MeshReplica` duck-types :class:`~bioengine_tpu.serving.replica.
Replica` exactly like ``RemoteReplica`` does, so the WHOLE serving plane
applies to a multi-host deployment unchanged: the router and global
scheduler route to it (``call_bounded`` / ``call_batch``), the health
loop restarts it, drain/undeploy tear it down, the circuit breaker
ejects it, chip accounting releases every shard's lease under ONE
replica id, and tracing/flight events flow from the same
instrumentation points.

Under it, :class:`CrossHostEngine` drives the per-host shards — each a
normal host-side ``Replica`` whose instance holds only its slice of the
model in a PR 5 ``InferenceEngine`` over that host's lease. Activations
cross hosts inside ordinary ``replica_call`` frames, where the PR 3
codec already moves any >=1KiB ndarray as a zero-copy OOB payload (shm
fast path on a shared machine) — collectives bootstrap on the existing
transport, no second data plane. The whole exchange is gated on the
capability-negotiated ``mesh1`` proto: the controller only plans shards
onto hosts that declared it, and a host refuses a ``mesh_shard`` start
from a controller that never advertised it.

Degradation: any shard failure marks the mesh UNHEALTHY (one
``mesh.degrade`` flight event names the shard); the controller's normal
restart path then re-plans — onto the surviving hosts, collapsing to a
single-host fallback mesh when only one remains (unless the config
forbids it). A host REJOIN does not re-adopt mesh shards (the mesh's
identity spans hosts); the rejoining host is told to drop its copies
and the re-plan takes over.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Callable, Optional

import numpy as np

from bioengine_tpu.rpc import protocol
from bioengine_tpu.serving.errors import (
    DeadlineExceeded,
    ReplicaUnavailableError,
    is_caller_timeout,
    is_retryable,
)
from bioengine_tpu.serving.mesh_plan import MeshConfig, MeshPlan
from bioengine_tpu.serving.replica import (
    DEFAULT_DRAIN_TIMEOUT_S,
    ROUTABLE_STATES,
    ReplicaState,
    ReplicaStateMixin,
)
from bioengine_tpu.utils import flight, metrics, tracing

# cross-host data-plane accounting: how many activation bytes hop
# between shards and what the hops cost — the number that says whether
# a pipeline split is transfer-bound (surfaces in get_app_status and
# the multihost_mesh bench stage)
MESH_TRANSFER_BYTES = metrics.counter(
    "mesh_transfer_bytes_total",
    "activation bytes exchanged between mesh shards (both directions)",
    ("app", "deployment"),
)
MESH_TRANSFER_SECONDS = metrics.counter(
    "mesh_transfer_seconds_total",
    "wall seconds spent in cross-shard stage calls (transfer + compute)",
    ("app", "deployment"),
)
MESH_STAGE_CALLS = metrics.counter(
    "mesh_stage_calls_total",
    "stage invocations dispatched to mesh shards",
    ("app", "deployment"),
)


class CrossHostEngine:
    """Drives one logical forward across per-host engine shards.

    ``call_stage(shard, method, args, timeout_s)`` is the transport —
    injected by :class:`MeshReplica` (controller → host ``replica_call``
    over the RPC plane) or by tests/the dryrun (in-process stubs), so
    the composition math is checkable without a cluster.

    Composition by ``kind``:

    - ``pipeline``: sequential hops, stage k's output array is stage
      k+1's input. Throughput comes from co-batched requests (the PR 8
      scheduler coalesces; each hop carries the whole group's batch).
    - ``dp``: the batch splits across shards (``np.array_split`` on
      axis 0), shards run concurrently, outputs concatenate in order.
    - ``tp``: every shard sees the full input and returns a PARTIAL
      output; the driver sums — the host-mediated all-reduce of a
      Megatron block (shard halves exchange activations through the
      driver rather than ICI until real DCN collectives exist).
    """

    def __init__(
        self,
        config: MeshConfig,
        n_shards: int,
        call_stage: Callable[..., Any],
        app_id: str = "?",
        deployment: str = "?",
    ):
        self.config = config
        self.n_shards = n_shards
        self._call_stage = call_stage
        self.transfer_bytes = 0
        self.transfer_seconds = 0.0
        self.stage_calls = 0
        self._m_bytes = MESH_TRANSFER_BYTES.labels(app_id, deployment)
        self._m_seconds = MESH_TRANSFER_SECONDS.labels(app_id, deployment)
        self._m_calls = MESH_STAGE_CALLS.labels(app_id, deployment)

    async def _stage(
        self, shard: int, inputs: Any, timeout_s: Optional[float]
    ) -> Any:
        t0 = time.monotonic()
        out = await self._call_stage(
            shard, self.config.stage_method, [shard, inputs], timeout_s
        )
        dt = time.monotonic() - t0
        # the codec's own payload walk (depth-guarded) — activation
        # accounting agrees with what the wire actually moves
        moved = protocol.payload_nbytes(inputs) + protocol.payload_nbytes(
            out
        )
        self.stage_calls += 1
        self.transfer_bytes += moved
        self.transfer_seconds += dt
        self._m_calls.inc()
        self._m_bytes.inc(moved)
        self._m_seconds.inc(dt)
        return out

    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        per_hop = self.config.resolved_stage_timeout_s()
        if deadline is None:
            return per_hop
        left = deadline - time.monotonic()
        if left <= 0:
            # an earlier hop ate the whole composition budget — fail
            # fast HERE instead of serializing a multi-MB activation
            # onto the wire with a dead (negative) timeout
            raise DeadlineExceeded(
                f"mesh {self.config.kind} composition budget exhausted "
                f"mid-run ({self.n_shards} shards)"
            )
        return min(per_hop, left) if per_hop is not None else left

    async def run(
        self, inputs: Any, timeout_s: Optional[float] = None
    ) -> Any:
        """One logical forward. ``timeout_s`` bounds the WHOLE
        composition; each hop additionally respects the per-stage
        budget (``mesh.stage_timeout_s`` /
        ``BIOENGINE_MESH_STAGE_TIMEOUT_S``)."""
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        kind = self.config.kind
        with tracing.trace_span(
            "mesh.run", kind=kind, shards=self.n_shards
        ):
            if kind == "pipeline":
                act = inputs
                for k in range(self.n_shards):
                    act = await self._stage(k, act, self._remaining(deadline))
                return act
            if kind == "dp":
                # a batch smaller than the shard count would split into
                # EMPTY tails — skip them (every dp shard holds the full
                # model, so any prefix of shards serves the request)
                # rather than paying a cross-host round trip per surplus
                # shard and skewing the transfer accounting with
                # phantom hops
                parts = [
                    p
                    for p in np.array_split(
                        np.asarray(inputs), self.n_shards
                    )
                    if len(p)
                ]
                outs = await asyncio.gather(
                    *(
                        self._stage(k, part, self._remaining(deadline))
                        for k, part in enumerate(parts)
                    )
                )
                return np.concatenate(
                    [np.asarray(o) for o in outs], axis=0
                )
            if kind == "tp":
                outs = await asyncio.gather(
                    *(
                        self._stage(k, inputs, self._remaining(deadline))
                        for k in range(self.n_shards)
                    )
                )
                total = np.asarray(outs[0])
                for o in outs[1:]:
                    total = total + np.asarray(o)
                return total
            raise ValueError(f"unknown mesh kind '{kind}'")

    def stats(self) -> dict:
        return {
            "stage_calls": self.stage_calls,
            "transfer_bytes": self.transfer_bytes,
            "transfer_seconds": round(self.transfer_seconds, 6),
            "transfer_bytes_per_sec": round(
                self.transfer_bytes / self.transfer_seconds, 1
            )
            if self.transfer_seconds > 0
            else None,
        }


class MeshReplica(ReplicaStateMixin):
    """One logical deployment over the shards of a :class:`MeshPlan`.

    Chip accounting: every shard's chips are leased (by the controller)
    under THIS replica's id, so ``ClusterState.mark_replica_dead(
    replica_id)`` releases the whole mesh — host deaths, restarts, and
    undeploy leak nothing without any mesh-specific bookkeeping."""

    is_remote = True
    is_mesh = True

    def __init__(
        self,
        app_id: str,
        deployment_name: str,
        plan: MeshPlan,
        call_host: Callable[..., Any],   # async (service_id, method, *a, **kw)
        payload: dict,
        max_ongoing_requests: int = 10,
        log_sink: Optional[Callable[[str, str], None]] = None,
        drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
        stream_host: Optional[Callable[..., Any]] = None,
    ):
        self.app_id = app_id
        self.deployment_name = deployment_name
        self.replica_id = f"{deployment_name}-mesh-{uuid.uuid4().hex[:8]}"
        self.plan = plan
        self.config: MeshConfig = plan.config
        # flattened view for flight/status; per-shard detail lives in
        # describe()["mesh"]["shards"]. host_id is the joined shard-host
        # set — display/logging only. NB it CAN equal a single host's id
        # (a 1-host plan or the fallback mesh), so rejoin re-adoption is
        # guarded explicitly by is_mesh in the controller's
        # _readopt_replica, not by this string's shape.
        self.device_ids = [d for s in plan.shards for d in s.device_ids]
        self.host_id = "+".join(plan.hosts)
        self.max_ongoing_requests = max_ongoing_requests
        self.drain_timeout_s = drain_timeout_s
        self.state = ReplicaState.STARTING
        self.started_at = time.time()
        self._started_mono = time.monotonic()
        self.last_error: Optional[str] = None
        self._payload = payload
        self._call_host = call_host
        self._stream_host = stream_host
        self._ongoing = 0
        self._total_requests = 0
        self._idle_event = asyncio.Event()
        self._idle_event.set()
        self._log_sink = log_sink
        self._degraded = False
        # hosts whose shard failed during this mesh's life — the
        # restart path steers the re-plan around them (scored as
        # last-resort by plan_mesh's `avoided` feature, so a sole
        # survivor is still usable)
        self.degraded_hosts: set[str] = set()
        self.ttfr: dict[str, Any] = {}
        self.promoted_from_warm_pool = False
        self._first_request_done = False
        self.engine = CrossHostEngine(
            self.config,
            len(plan.shards),
            self._call_shard_stage,
            app_id=app_id,
            deployment=deployment_name,
        )

    def _log(self, line: str) -> None:
        if self._log_sink:
            self._log_sink(self.replica_id, line)

    def shard_replica_id(self, stage: int) -> str:
        return f"{self.replica_id}-s{stage}"

    # ---- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        started: list[int] = []
        shard_states: list[ReplicaState] = []
        try:
            for shard in self.plan.shards:
                rid = self.shard_replica_id(shard.stage)
                self._log(
                    f"starting shard {rid} (stage {shard.stage}) on "
                    f"host {shard.host_id} chips {shard.device_ids}"
                )
                result = await self._call_host(
                    shard.service_id,
                    "start_replica",
                    replica_id=rid,
                    device_ids=list(shard.device_ids),
                    max_ongoing_requests=self.max_ongoing_requests,
                    payload=self._payload,
                    mesh_shard={
                        "stage": shard.stage,
                        "n_stages": self.config.stages,
                        "kind": self.config.kind,
                        "axes": dict(self.config.axes),
                        # the parent identity a RECOVERING controller
                        # groups surviving shards by when it rebuilds
                        # the MeshReplica from host inventory
                        "mesh_replica_id": self.replica_id,
                    },
                )
                shard_states.append(ReplicaState(result["state"]))
                started.append(shard.stage)
            self.state = (
                ReplicaState.TESTING
                if any(s == ReplicaState.TESTING for s in shard_states)
                else ReplicaState.HEALTHY
            )
            self.ttfr["init_seconds"] = round(
                time.monotonic() - self._started_mono, 4
            )
            flight.record(
                "mesh.establish",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                kind=self.config.kind,
                mesh_shape=self.config.mesh_shape(),
                hosts=self.plan.hosts,
                cross_host=self.plan.cross_host,
                stages=self.config.stages,
            )
            self._log(
                f"mesh established: {self.config.kind} x{self.config.stages} "
                f"over {self.plan.hosts} (state={self.state})"
            )
        except Exception as e:
            self.last_error = str(e)[-2000:]
            self.state = ReplicaState.UNHEALTHY
            self._log(f"mesh start failed: {e}")
            # release whatever shards DID start; leases release when the
            # controller marks this replica dead
            for stage in started:
                shard = self.plan.shards[stage]
                try:
                    await self._call_host(
                        shard.service_id,
                        "stop_replica",
                        self.shard_replica_id(stage),
                    )
                except Exception as rollback_err:  # noqa: BLE001 — rollback is best-effort
                    self._log(
                        f"shard {stage} rollback stop failed "
                        f"(tolerated): {rollback_err}"
                    )
            raise

    async def check_health(self) -> ReplicaState:
        if self.state in (
            ReplicaState.STOPPED,
            ReplicaState.UNHEALTHY,
            ReplicaState.DRAINING,
        ):
            return self.state

        async def one(shard) -> tuple:
            try:
                result = await asyncio.wait_for(
                    self._call_host(
                        shard.service_id,
                        "replica_health",
                        self.shard_replica_id(shard.stage),
                    ),
                    timeout=30.0,
                )
                return shard, ReplicaState(result["state"]), result.get(
                    "last_error"
                )
            except Exception as e:  # noqa: BLE001 — transport error = shard gone
                return shard, ReplicaState.UNHEALTHY, (
                    f"host '{shard.host_id}' unreachable: {e}"
                )

        results = await asyncio.gather(
            *(one(s) for s in self.plan.shards)
        )
        # ANY shard that cannot take stage calls fails the whole mesh —
        # a shard parked in DRAINING/STOPPED (host-side drain, admin
        # action) serves nothing, and a mesh left HEALTHY around it
        # would route every request into ReplicaUnavailableError
        # forever instead of being re-planned
        bad = [
            (s, err or f"shard state {state.value}")
            for s, state, err in results
            if state not in (ReplicaState.HEALTHY, ReplicaState.TESTING)
        ]
        if bad:
            shard, err = bad[0]
            self.last_error = err
            self.state = ReplicaState.UNHEALTHY
            # EVERY failed shard's host feeds the re-plan avoid set (a
            # shared rack fault can take two shards down in one tick);
            # the one-shot degrade event still names the first
            for other, _ in bad[1:]:
                self.degraded_hosts.add(other.host_id)
            self._note_degraded(shard, err)
        elif any(state == ReplicaState.TESTING for _, state, _ in results):
            self.state = ReplicaState.TESTING
        elif self.state != ReplicaState.PROBATION:
            # gray failure is invisible to health checks by definition:
            # a controller-assigned PROBATION (latency outlier,
            # serving/outlier.py) survives an all-shards-healthy check
            # — only latency evidence from probe traffic clears it
            # (same guard as Replica/RemoteReplica.check_health)
            self.state = ReplicaState.HEALTHY
        return self.state

    async def drain(self, timeout_s: Optional[float] = None) -> bool:
        if self.state in ROUTABLE_STATES + (ReplicaState.INITIALIZING,):
            self.state = ReplicaState.DRAINING
            self._log(f"draining mesh ({self._ongoing} in-flight)")
            flight.record(
                "replica.drain",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                host=self.host_id,
                in_flight=self._ongoing,
            )
        timeout = self.drain_timeout_s if timeout_s is None else timeout_s
        started = time.monotonic()
        # host-side drains run concurrently on ONE shared budget
        await asyncio.gather(
            *(
                self._drain_shard(s, timeout)
                for s in self.plan.shards
            ),
            return_exceptions=True,
        )
        if self._ongoing == 0:
            return True
        remaining = max(0.0, timeout - (time.monotonic() - started))
        try:
            await asyncio.wait_for(self._idle_event.wait(), remaining)
            return True
        except asyncio.TimeoutError:
            self._log(f"mesh drain timed out ({self._ongoing} stranded)")
            return False

    async def _drain_shard(self, shard, timeout: float) -> None:
        try:
            await asyncio.wait_for(
                self._call_host(
                    shard.service_id,
                    "drain_replica",
                    self.shard_replica_id(shard.stage),
                    timeout,
                ),
                timeout=timeout + 5.0,
            )
        except Exception as e:  # noqa: BLE001 — a dead host has trivially drained
            self._log(
                f"shard {shard.stage} drain failed (tolerated): {e}"
            )

    async def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        if self.state in (
            ReplicaState.HEALTHY,
            ReplicaState.TESTING,
            ReplicaState.DRAINING,
        ):
            await self.drain(drain_timeout_s)
        self.state = ReplicaState.STOPPED

        async def stop_shard(shard) -> None:
            try:
                await asyncio.wait_for(
                    self._call_host(
                        shard.service_id,
                        "stop_replica",
                        self.shard_replica_id(shard.stage),
                    ),
                    timeout=15.0,
                )
            except Exception as e:  # noqa: BLE001 — host already gone is stopped
                self._log(
                    f"shard {shard.stage} stop failed (tolerated): {e}"
                )

        await asyncio.gather(*(stop_shard(s) for s in self.plan.shards))
        flight.record(
            "mesh.teardown",
            replica=self.replica_id,
            app=self.app_id,
            deployment=self.deployment_name,
            hosts=self.plan.hosts,
            **self.engine.stats(),
        )
        self._log("mesh stopped")

    def _note_degraded(self, shard, err) -> None:
        """Record the ONE ``mesh.degrade`` event for this mesh's life —
        fired wherever the shard failure is first observed (a stage
        call's transport error usually beats the health loop; the
        breaker may flip the state before check_health ever runs)."""
        self.degraded_hosts.add(shard.host_id)
        if self._degraded:
            return
        self._degraded = True
        flight.record(
            "mesh.degrade",
            severity="warning",
            replica=self.replica_id,
            app=self.app_id,
            deployment=self.deployment_name,
            stage=shard.stage,
            host=shard.host_id,
            error=str(err)[:300],
        )
        self._log(
            f"mesh degraded: stage {shard.stage} on {shard.host_id}: {err}"
        )

    # ---- request path -------------------------------------------------------

    async def _call_shard_stage(
        self,
        shard_index: int,
        method: str,
        args: list,
        timeout_s: Optional[float],
        kwargs: Optional[dict] = None,
    ) -> Any:
        """The CrossHostEngine's transport (and the route for non-entry
        control/status methods, which carry ``kwargs``): one hop
        through the existing replica RPC plane. Activation ndarrays in
        ``args`` and the result ride the PR 3 OOB frames (shm on a
        shared machine) — no mesh-specific wire format."""
        shard = self.plan.shards[shard_index]
        extra: dict = {}
        if timeout_s is not None:
            extra = {"timeout_s": timeout_s, "rpc_timeout": timeout_s + 5.0}
        try:
            with tracing.trace_span(
                "mesh.stage",
                replica=self.replica_id,
                stage=shard.stage,
                host=shard.host_id,
            ):
                return await self._call_host(
                    shard.service_id,
                    "replica_call",
                    self.shard_replica_id(shard.stage),
                    method,
                    args,
                    kwargs or {},
                    **extra,
                )
        except KeyError as e:
            # the host's service vanished from the router registry —
            # typed so the handle fails over / parks for the re-plan
            self._note_degraded(shard, e)
            raise ReplicaUnavailableError(
                f"mesh shard {shard.stage} host '{shard.host_id}' "
                f"service vanished: {e}"
            ) from e
        except Exception as e:
            # a transport-classified stage failure is the data-plane
            # sighting of a degraded mesh (it usually precedes the
            # health loop's verdict); a member's own expired budget says
            # nothing about shard health
            if is_retryable(e) and not is_caller_timeout(e):
                self._note_degraded(shard, e)
            raise

    async def call(self, method: str, *args, **kwargs) -> Any:
        return await self.call_bounded(method, args, kwargs)

    async def call_bounded(
        self,
        method: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Any:
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"mesh replica {self.replica_id} not healthy ({self.state})"
            )
        kwargs = kwargs or {}
        self._ongoing += 1
        self._idle_event.clear()
        self._total_requests += 1
        try:
            if method in self.config.entry_methods:
                # the mesh driver owns entry methods: the single
                # positional payload is the model input, composed across
                # shards per the config's kind
                if kwargs or len(args) != 1:
                    raise TypeError(
                        f"mesh entry method '{method}' takes exactly one "
                        f"positional input (got args={len(args)}, "
                        f"kwargs={sorted(kwargs)}) — per-request options "
                        f"don't fan across shards"
                    )
                result = await self.engine.run(args[0], timeout_s=timeout_s)
            else:
                # control-plane / status methods route to stage 0
                result = await self._call_shard_stage(
                    0, method, list(args), timeout_s, kwargs=kwargs
                )
            if not self._first_request_done:
                self._first_request_done = True
                self.ttfr["ttfr_seconds"] = round(
                    time.monotonic() - self._started_mono, 4
                )
                flight.record(
                    "replica.first_request",
                    replica=self.replica_id,
                    app=self.app_id,
                    deployment=self.deployment_name,
                    host=self.host_id,
                    method=method,
                    ttfr_seconds=self.ttfr["ttfr_seconds"],
                    warm_pool=False,
                )
            return result
        finally:
            self._ongoing -= 1
            if self._ongoing == 0:
                self._idle_event.set()

    async def call_stream(self, method: str, *args, **kwargs):
        """Token stream through the mesh: the stream is driven by stage
        0's replica (whose DecodeLoop holds the KV cache for the
        sequence); other stages serve it via the instance's own
        cross-shard calls, exactly like non-entry unary methods route.
        Duck-types ``Replica.call_stream`` so DeploymentHandle's
        streaming failover applies to mesh deployments unchanged."""
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"mesh replica {self.replica_id} not healthy ({self.state})"
            )
        if self._stream_host is None:
            raise ReplicaUnavailableError(
                f"mesh replica {self.replica_id}: control plane has no "
                "streaming bridge"
            )
        shard = self.plan.shards[0]
        self._ongoing += 1
        self._idle_event.clear()
        self._total_requests += 1
        try:
            with tracing.trace_span(
                "mesh.stream",
                replica=self.replica_id,
                stage=shard.stage,
                host=shard.host_id,
            ):
                agen = self._stream_host(
                    shard.service_id,
                    "replica_stream",
                    self.shard_replica_id(shard.stage),
                    method,
                    list(args),
                    kwargs or {},
                )
                async for item in agen:
                    if not self._first_request_done:
                        self._first_request_done = True
                        self.ttfr["ttfr_seconds"] = round(
                            time.monotonic() - self._started_mono, 4
                        )
                    yield item
        except KeyError as e:
            self._note_degraded(shard, e)
            raise ReplicaUnavailableError(
                f"mesh shard {shard.stage} host '{shard.host_id}' "
                f"service vanished: {e}"
            ) from e
        finally:
            self._ongoing -= 1
            if self._ongoing == 0:
                self._idle_event.set()

    async def call_batch(
        self,
        method: str,
        requests: list,
        timeout_s: Optional[float] = None,
    ) -> list:
        """A scheduler-coalesced group against the mesh: members run
        concurrently through the normal per-call path (pipeline hops
        already carry each member's batch; per-member failures stay
        isolated, local-envelope style like ``Replica.call_batch``)."""

        async def one(r: dict) -> dict:
            try:
                result = await self.call_bounded(
                    method,
                    tuple(r.get("args") or ()),
                    dict(r.get("kwargs") or {}),
                    timeout_s=timeout_s,
                )
                return {"ok": True, "result": result}
            except Exception as e:  # noqa: BLE001 — per-member isolation
                return {"ok": False, "exception": e}

        return await asyncio.gather(*(one(r) for r in requests))

    def mark_promoted(self) -> None:
        """Mesh replicas don't sit in warm pools (their chips span
        hosts); promotion re-anchoring is a no-op kept for duck-type
        completeness."""
        self.promoted_from_warm_pool = True

    @property
    def load(self) -> float:
        return self._ongoing / max(1, self.max_ongoing_requests)

    def describe(self) -> dict:
        mesh = self.plan.describe()
        mesh["transfer"] = self.engine.stats()
        mesh["shard_replica_ids"] = [
            self.shard_replica_id(s.stage) for s in self.plan.shards
        ]
        return {
            "replica_id": self.replica_id,
            "deployment": self.deployment_name,
            "state": self.state.value,
            "device_ids": self.device_ids,
            "host_id": self.host_id,
            "ongoing_requests": self._ongoing,
            # like RemoteReplica: no queued_requests key — the shard
            # semaphores live host-side; a missing key reads as unknown
            "total_requests": self._total_requests,
            "load": self.load,
            "mesh": mesh,
            "cold_start": dict(self.ttfr),
            "uptime_seconds": time.monotonic() - self._started_mono,
            "last_error": self.last_error,
        }
