"""Durable control-plane state: write-ahead intent journal + snapshot.

The controller holds everything — deployment specs, chip leases,
scheduler queues, warm pools — in one process's memory. This module
makes the *declarative* slice of that state (what SHOULD be running:
deployed apps with their full ``DeploymentSpec``s, admin bindings, and
the controller epoch) survive a crash or upgrade:

- **Intent journal** (``journal.log``): an append-only record stream,
  one CRC-guarded line per *intent commit* — ``deploy`` / ``undeploy``
  / ``scale`` accepted, ``epoch`` minted, ``admins`` bound. Never
  per-request: the journal write sits on the control path, not the
  data path. Each line is ``J1 <crc32hex> <json>``; replay stops
  cleanly at the first record whose CRC or JSON fails (a torn tail
  from a crash mid-append loses at most that one uncommitted record).
- **Compacted snapshot** (``snapshot.json``): the folded state, written
  atomically (tmp file + fsync + rename) every
  ``BIOENGINE_JOURNAL_SNAPSHOT_EVERY`` journal records and at
  recovery-complete; the journal restarts empty after each snapshot, so
  replay cost is bounded by the snapshot cadence, not uptime.
- **Epoch**: every controller start mints ``last_epoch + 1`` and
  persists it BEFORE serving, so a wedged-then-revived old controller
  can never out-epoch its replacement. The epoch is stamped on host
  verbs (``register_host`` / ``start_replica`` / ``drain_replica`` /
  ``stop_replica``) and hosts reject lower-epoch verbs typed
  (:class:`~bioengine_tpu.serving.errors.StaleEpochError`) — the
  split-brain fence.

The journal directory is ``BIOENGINE_CONTROL_DIR``; unset means the
controller runs memory-only exactly as before (tests, toys). What is
deliberately NOT journaled: replica placements and chip leases — those
are *observed* state, reconciled at recovery from what live hosts
actually report (``register_host`` warm-replica inventory), because
the hosts are the ground truth the journal could only approximate.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import zlib
from pathlib import Path
from typing import Any, Optional

from bioengine_tpu.utils import flight, metrics
from bioengine_tpu.utils.logger import create_logger

SNAPSHOT_NAME = "snapshot.json"
JOURNAL_NAME = "journal.log"
_MAGIC = "J1"

JOURNAL_RECORDS = metrics.counter(
    "journal_records_total",
    "intent records appended to the control-plane journal",
)
JOURNAL_SNAPSHOTS = metrics.counter(
    "journal_snapshots_total",
    "compacted control-plane snapshots written (atomic rename)",
)
JOURNAL_REPLAYED = metrics.counter(
    "journal_replay_records_total",
    "journal records replayed into controller state at recovery",
)


# ---------------------------------------------------------------------------
# DeploymentSpec <-> dict (the full deployment_config vocabulary:
# scheduling / slo / warm_pool / mesh / batching blocks all round-trip)
# ---------------------------------------------------------------------------


def spec_to_dict(spec) -> dict:
    """Serialize a ``DeploymentSpec`` for the journal. Everything
    round-trips except ``instance_factory`` (a live callable): specs
    with a ``remote_payload`` rebuild it from the payload's shipped
    sources at recovery; purely-local specs without one are recorded
    but can only be re-served by an explicit redeploy."""

    def block(cfg) -> Optional[dict]:
        return None if cfg is None else dataclasses.asdict(cfg)

    return {
        "name": spec.name,
        "num_replicas": spec.num_replicas,
        "min_replicas": spec.min_replicas,
        "max_replicas": spec.max_replicas,
        "chips_per_replica": spec.chips_per_replica,
        "max_ongoing_requests": spec.max_ongoing_requests,
        "autoscale": spec.autoscale,
        "target_load": spec.target_load,
        "max_batch": spec.max_batch,
        "max_wait_ms": spec.max_wait_ms,
        "scheduling": block(spec.scheduling),
        "slo": block(spec.slo),
        "warm_pool": block(spec.warm_pool),
        "mesh": block(spec.mesh),
        "remote_payload": spec.remote_payload,
    }


class PayloadInstanceFactory:
    """Lazy local-build factory for a journal-recovered spec: on first
    call it writes the remote payload's shipped sources to a workdir
    and runs the standard AppBuilder — the same build a worker host
    performs in ``start_replica`` — returning the instance. Recovery
    itself never builds anything; only an actual LOCAL placement pays
    (remote placements ship the payload to the host as always)."""

    def __init__(self, payload: dict, workdir_root: Optional[Path] = None,
                 make_handle: Any = None):
        self._payload = payload
        self._workdir_root = workdir_root
        self._make_handle = make_handle
        self._factory = None

    def __call__(self):
        if self._factory is None:
            self._factory = self._build()
        return self._factory()

    def _build(self):
        import tempfile

        from bioengine_tpu.apps.builder import AppBuilder

        payload = self._payload
        root = Path(
            self._workdir_root
            or tempfile.mkdtemp(prefix="bioengine-journal-build-")
        )
        app_id = payload["app_id"]
        src = root / f"recovered-{app_id}"
        src.mkdir(parents=True, exist_ok=True)
        for rel, text in payload["files"].items():
            target = src / rel
            if not target.resolve().is_relative_to(src.resolve()):
                raise ValueError(f"payload path escapes app dir: {rel}")
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_text(text)
        builder = AppBuilder(workdir_root=root / "apps")
        built = builder.build(
            app_id=app_id,
            local_path=src,
            deployment_kwargs=payload.get("deployment_kwargs"),
            env_vars=payload.get("env_vars"),
            make_handle=self._make_handle,
        )
        spec = next(
            s for s in built.specs if s.name == payload["deployment"]
        )
        return spec.instance_factory


class UnrecoverableFactory:
    """Factory stand-in for a journaled spec with no remote payload:
    the intent survives (status shows it, operators see what was lost)
    but a local placement fails loudly instead of serving garbage."""

    def __init__(self, app_id: str, deployment: str):
        self.app_id = app_id
        self.deployment = deployment

    def __call__(self):
        raise RuntimeError(
            f"{self.app_id}/{self.deployment} was recovered from the "
            f"journal without a remote payload — its instance_factory "
            f"was a live callable that died with the old controller; "
            f"redeploy the app to restore it"
        )


def spec_from_dict(d: dict, app_id: str, make_handle: Any = None):
    """Rebuild a ``DeploymentSpec`` from its journal form."""
    from bioengine_tpu.serving.controller import DeploymentSpec
    from bioengine_tpu.serving.mesh_plan import MeshConfig
    from bioengine_tpu.serving.scheduler import SchedulingConfig
    from bioengine_tpu.serving.slo import SLOConfig
    from bioengine_tpu.serving.warm_pool import WarmPoolConfig

    def block(cls, data):
        if data is None:
            return None
        kwargs = dict(data)
        if cls is MeshConfig:
            kwargs["entry_methods"] = tuple(
                kwargs.get("entry_methods") or ()
            )
        return cls(**kwargs)

    payload = d.get("remote_payload")
    if payload is not None:
        factory: Any = PayloadInstanceFactory(
            payload, make_handle=make_handle
        )
    else:
        factory = UnrecoverableFactory(app_id, d["name"])
    return DeploymentSpec(
        name=d["name"],
        instance_factory=factory,
        num_replicas=int(d.get("num_replicas", 1)),
        min_replicas=int(d.get("min_replicas", 1)),
        max_replicas=int(d.get("max_replicas", 3)),
        chips_per_replica=int(d.get("chips_per_replica", 0)),
        max_ongoing_requests=int(d.get("max_ongoing_requests", 10)),
        autoscale=bool(d.get("autoscale", True)),
        target_load=float(d.get("target_load", 0.7)),
        max_batch=d.get("max_batch"),
        max_wait_ms=d.get("max_wait_ms"),
        scheduling=block(SchedulingConfig, d.get("scheduling")),
        slo=block(SLOConfig, d.get("slo")),
        warm_pool=block(WarmPoolConfig, d.get("warm_pool")),
        mesh=block(MeshConfig, d.get("mesh")),
        remote_payload=payload,
    )


# ---------------------------------------------------------------------------
# secret redaction (CLI inspection — journals carry remote payloads
# whose env_vars may hold tokens)
# ---------------------------------------------------------------------------

_SECRET_KEY_MARKERS = ("token", "secret", "password", "api_key", "apikey",
                       "credential", "auth")


def redact_secrets(obj: Any) -> Any:
    """Recursively mask values under secret-shaped keys and shrink the
    bulky ``files`` payload to a name->size map — what ``bioengine
    debug journal`` prints. The on-disk journal is untouched."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            lk = str(k).lower()
            if any(m in lk for m in _SECRET_KEY_MARKERS) and isinstance(
                v, (str, bytes)
            ):
                out[k] = "***redacted***"
            elif lk == "files" and isinstance(v, dict):
                out[k] = {
                    name: f"<{len(text)} chars>"
                    for name, text in v.items()
                }
            else:
                out[k] = redact_secrets(v)
        return out
    if isinstance(obj, list):
        return [redact_secrets(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class JournalState:
    """Folded declarative state after snapshot load + journal replay."""

    epoch: int = 0
    seq: int = 0
    apps: dict[str, dict] = dataclasses.field(default_factory=dict)
    admins: list[str] = dataclasses.field(default_factory=list)
    snapshot_loaded: bool = False
    records_replayed: int = 0
    torn_tail: bool = False          # replay stopped at a bad record
    recovering_snapshot: bool = False  # snapshot written mid-recovery

    def apply(self, record: dict) -> None:
        op = record.get("op")
        data = record.get("data") or {}
        self.seq = max(self.seq, int(record.get("seq", 0)))
        self.epoch = max(self.epoch, int(record.get("epoch", 0)))
        if op == "epoch":
            pass  # the epoch max above is the whole effect
        elif op == "deploy":
            self.apps[data["app_id"]] = {
                "specs": data["specs"],
                "acl": data.get("acl"),
            }
        elif op == "undeploy":
            self.apps.pop(data.get("app_id", ""), None)
        elif op == "scale":
            app = self.apps.get(data.get("app_id", ""))
            if app:
                for spec in app["specs"]:
                    if spec.get("name") == data.get("deployment"):
                        spec["num_replicas"] = int(data["num_replicas"])
        elif op == "admins":
            self.admins = list(data.get("admins") or [])
        # unknown ops are skipped: an OLD controller replaying a NEWER
        # journal (downgrade) keeps what it understands


class ControlJournal:
    """Write-ahead intent journal + compacted snapshot in one
    directory. All writes are synchronous file appends with fsync —
    acceptable because they happen at intent commit (deploy/undeploy/
    scale), never per request."""

    def __init__(self, directory: str | Path,
                 snapshot_every: Optional[int] = None):
        self.directory = Path(directory).expanduser()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.snapshot_every = (
            snapshot_every
            if snapshot_every is not None
            else int(os.environ.get("BIOENGINE_JOURNAL_SNAPSHOT_EVERY", "64"))
        )
        self.logger = create_logger("journal", log_file="off")
        self.epoch = 0
        self.seq = 0
        self._records_since_snapshot = 0
        self.records_written = 0
        self.snapshots_written = 0
        # the folded view the periodic snapshot writes; refreshed via
        # set_snapshot_state, or pulled lazily from snapshot_provider
        # at snapshot time (so the owner doesn't pay a full-fleet
        # serialization on every append — only 1-in-snapshot_every
        # appends actually compacts)
        self._snapshot_state: dict = {"apps": {}, "admins": []}
        self._recovering = False
        # optional () -> (apps, admins, recovering) callable
        self.snapshot_provider = None

    # ---- construction -------------------------------------------------------

    @classmethod
    def from_env(cls) -> Optional["ControlJournal"]:
        directory = os.environ.get("BIOENGINE_CONTROL_DIR")
        if not directory:
            return None
        return cls(directory)

    @property
    def snapshot_path(self) -> Path:
        return self.directory / SNAPSHOT_NAME

    @property
    def journal_path(self) -> Path:
        return self.directory / JOURNAL_NAME

    # ---- load / replay ------------------------------------------------------

    def load(self) -> JournalState:
        """Snapshot + journal -> folded state. Never raises on bad
        content: a torn final record stops the replay cleanly (the
        records before it are kept) and the verdict rides
        ``state.torn_tail``."""
        state = JournalState()
        snap = self._read_snapshot()
        if snap is not None:
            state.snapshot_loaded = True
            state.epoch = int(snap.get("epoch", 0))
            state.seq = int(snap.get("seq", 0))
            state.apps = dict(snap.get("apps") or {})
            state.admins = list(snap.get("admins") or [])
            state.recovering_snapshot = bool(snap.get("recovering", False))
        records, torn, valid_bytes = self._scan()
        if torn:
            state.torn_tail = True
            self._truncate_torn_tail(valid_bytes)
        for record in records:
            if int(record.get("seq", 0)) <= state.seq and record.get(
                "op"
            ) != "epoch":
                continue  # already folded into the snapshot
            state.apply(record)
            state.records_replayed += 1
        if state.records_replayed:
            JOURNAL_REPLAYED.inc(state.records_replayed)
        self.epoch = state.epoch
        self.seq = state.seq
        self._snapshot_state = {
            "apps": dict(state.apps),
            "admins": list(state.admins),
        }
        flight.record(
            "journal.replay",
            directory=str(self.directory),
            snapshot=state.snapshot_loaded,
            records=state.records_replayed,
            torn_tail=state.torn_tail,
            epoch=state.epoch,
            apps=len(state.apps),
        )
        return state

    def _read_snapshot(self) -> Optional[dict]:
        try:
            raw = self.snapshot_path.read_text()
        except OSError:
            return None
        try:
            snap = json.loads(raw)
        except json.JSONDecodeError as e:
            # an atomic-rename snapshot should never be torn; a corrupt
            # one is surfaced loudly but recovery proceeds from the
            # journal alone rather than refusing to start
            self.logger.error(f"snapshot unreadable ({e}); ignoring it")
            return None
        return snap if isinstance(snap, dict) else None

    def read_records(self):
        """Yield parsed journal records in order; yields ``None`` once
        (then stops) at the first CRC/parse failure — the torn-tail
        sentinel the caller turns into a flag."""
        records, torn, _ = self._scan()
        yield from records
        if torn:
            yield None

    @staticmethod
    def _parse_line(line: bytes) -> Optional[dict]:
        parts = line.split(b" ", 2)
        if len(parts) != 3 or parts[0] != _MAGIC.encode():
            return None
        crc_hex, body = parts[1], parts[2]
        try:
            expect = int(crc_hex, 16)
        except ValueError:
            return None
        if zlib.crc32(body) & 0xFFFFFFFF != expect:
            return None
        try:
            record = json.loads(body)
        except json.JSONDecodeError:
            return None
        return record if isinstance(record, dict) else None

    def _scan(self) -> tuple[list[dict], bool, int]:
        """Parse the journal -> ``(records, torn, valid_bytes)`` where
        ``valid_bytes`` is the length of the longest clean prefix. A
        final line without its newline terminator is torn by definition:
        ``append`` fsyncs the full line, so an unterminated tail means
        the crash happened mid-append and the record was never acked."""
        records: list[dict] = []
        try:
            raw = self.journal_path.read_bytes()
        except OSError:
            return records, False, 0
        pos = 0
        n = len(raw)
        while pos < n:
            nl = raw.find(b"\n", pos)
            if nl < 0:
                return records, True, pos
            line = raw[pos:nl]
            if line.strip():
                record = self._parse_line(line)
                if record is None:
                    return records, True, pos
                records.append(record)
            pos = nl + 1
        return records, False, pos

    def _truncate_torn_tail(self, valid_bytes: int) -> None:
        """Cut the journal back to its clean prefix so the NEXT append
        starts on a fresh line — without this, a new record written
        after a torn tail merges onto the partial line, fails CRC on
        the next replay, and takes every later record (including the
        minted epoch) down with it."""
        try:
            with open(self.journal_path, "r+b") as f:
                f.truncate(valid_bytes)
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            self.logger.error(f"torn-tail truncate failed: {e}")
            return
        self.logger.warning(
            f"journal torn tail truncated to {valid_bytes} bytes "
            f"(the uncommitted record is discarded)"
        )

    # ---- append / snapshot --------------------------------------------------

    def mint_epoch(self) -> int:
        """``last_epoch + 1``, persisted (journal record + fsync)
        BEFORE the new controller serves anything — the monotonic fence
        a revived old controller can never climb over."""
        self.epoch += 1
        self.append("epoch", {})
        return self.epoch

    def append(self, op: str, data: Optional[dict] = None) -> dict:
        self.seq += 1
        record = {
            "seq": self.seq,
            "ts": time.time(),
            "epoch": self.epoch,
            "op": op,
            "data": data or {},
        }
        body = json.dumps(record, separators=(",", ":"), default=str).encode()
        crc = zlib.crc32(body) & 0xFFFFFFFF
        line = b"%s %08x %s\n" % (_MAGIC.encode(), crc, body)
        with open(self.journal_path, "ab") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self.records_written += 1
        self._records_since_snapshot += 1
        JOURNAL_RECORDS.inc()
        if self._records_since_snapshot >= self.snapshot_every:
            self.write_snapshot()
        return record

    def set_snapshot_state(
        self, apps: dict, admins: list, recovering: bool = False
    ) -> None:
        """Refresh the folded view the next snapshot will persist
        (called by the controller at every intent commit — apps maps
        app_id to ``{"specs": [...], "acl": ...}``)."""
        self._snapshot_state = {"apps": apps, "admins": list(admins)}
        self._recovering = recovering

    def write_snapshot(self) -> Path:
        """Atomic compaction: write tmp + fsync + rename, then start a
        fresh journal (the snapshot subsumes every record up to
        ``seq``). A crash between rename and truncate only means a few
        records replay as no-ops (their seq is <= the snapshot's)."""
        if self.snapshot_provider is not None:
            apps, admins, recovering = self.snapshot_provider()
            self._snapshot_state = {"apps": apps, "admins": list(admins)}
            self._recovering = bool(recovering)
        snap = {
            "version": 1,
            "epoch": self.epoch,
            "seq": self.seq,
            "written_at": time.time(),
            "recovering": self._recovering,
            **self._snapshot_state,
        }
        tmp = self.snapshot_path.with_suffix(".json.tmp")
        data = json.dumps(snap, indent=2, default=str)
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)
        # fsync the DIRECTORY so the rename's metadata is durable
        # before the truncate below — without it a power loss could
        # persist an empty journal next to the OLD snapshot, losing
        # every record since the previous compaction
        dir_fd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        # the journal restarts empty — its records are folded in
        with open(self.journal_path, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        self._records_since_snapshot = 0
        self.snapshots_written += 1
        JOURNAL_SNAPSHOTS.inc()
        flight.record(
            "journal.snapshot",
            directory=str(self.directory),
            seq=self.seq,
            epoch=self.epoch,
            apps=len(self._snapshot_state.get("apps") or {}),
            recovering=self._recovering,
        )
        return self.snapshot_path

    # ---- inspection ---------------------------------------------------------

    def describe(self) -> dict:
        return {
            "directory": str(self.directory),
            "epoch": self.epoch,
            "seq": self.seq,
            "records_written": self.records_written,
            "snapshots_written": self.snapshots_written,
            "snapshot_every": self.snapshot_every,
            "journal_bytes": (
                self.journal_path.stat().st_size
                if self.journal_path.exists()
                else 0
            ),
            "snapshot_exists": self.snapshot_path.exists(),
        }

    def inspect(self, tail: int = 20) -> dict:
        """Offline dump for ``bioengine debug journal``: the snapshot
        plus the last ``tail`` journal records, secrets redacted."""
        records: list[dict] = []
        torn = False
        for record in self.read_records():
            if record is None:
                torn = True
                break
            records.append(record)
        snap = self._read_snapshot()
        return {
            "directory": str(self.directory),
            "snapshot": redact_secrets(snap) if snap else None,
            "journal_records": len(records),
            "torn_tail": torn,
            "tail": [redact_secrets(r) for r in records[-tail:]],
        }


__all__ = [
    "ControlJournal",
    "JournalState",
    "PayloadInstanceFactory",
    "UnrecoverableFactory",
    "redact_secrets",
    "spec_from_dict",
    "spec_to_dict",
]
