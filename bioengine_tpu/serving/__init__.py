from bioengine_tpu.serving.batching import ContinuousBatcher
from bioengine_tpu.serving.controller import (
    DeploymentHandle,
    DeploymentSpec,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.errors import (
    ApplicationError,
    DeadlineExceeded,
    NoHealthyReplicasError,
    ReplicaUnavailableError,
    RetryableTransportError,
)
from bioengine_tpu.serving.replica import Replica, ReplicaState

__all__ = [
    "ApplicationError",
    "ContinuousBatcher",
    "DeadlineExceeded",
    "DeploymentHandle",
    "DeploymentSpec",
    "NoHealthyReplicasError",
    "Replica",
    "ReplicaState",
    "ReplicaUnavailableError",
    "RequestOptions",
    "RetryableTransportError",
    "ServeController",
]
