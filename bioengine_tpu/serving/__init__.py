from bioengine_tpu.serving.batching import ContinuousBatcher
from bioengine_tpu.serving.controller import (
    DeploymentHandle,
    DeploymentSpec,
    RequestOptions,
    ServeController,
)
from bioengine_tpu.serving.errors import (
    AdmissionRejectedError,
    ApplicationError,
    DeadlineExceeded,
    NoHealthyReplicasError,
    ReplicaUnavailableError,
    RetryableTransportError,
    RouterClosedError,
    RouterSaturatedError,
    StaleEpochError,
    StaleTableError,
)
from bioengine_tpu.serving.router import (
    RouterCore,
    RoutingTablePublisher,
    StandaloneRouter,
    remote_replica_resolver,
    shared_object_resolver,
)
from bioengine_tpu.serving.journal import ControlJournal, JournalState
from bioengine_tpu.serving.mesh_plan import (
    MeshConfig,
    MeshPlan,
    MeshPlanError,
    plan_mesh,
)
from bioengine_tpu.serving.mesh_replica import CrossHostEngine, MeshReplica
from bioengine_tpu.serving.outlier import (
    DeploymentLatencyTracker,
    OutlierConfig,
)
from bioengine_tpu.serving.replica import Replica, ReplicaState
from bioengine_tpu.serving.scheduler import (
    DeploymentScheduler,
    HeuristicCostModel,
    LoadPredictor,
    SchedulingConfig,
)
from bioengine_tpu.serving.slo import SLOConfig, SLOEngine
from bioengine_tpu.serving.compile_tier import CompileCacheTier
from bioengine_tpu.serving.warm_pool import WarmPool, WarmPoolConfig

__all__ = [
    "AdmissionRejectedError",
    "ApplicationError",
    "ContinuousBatcher",
    "ControlJournal",
    "CrossHostEngine",
    "DeadlineExceeded",
    "DeploymentHandle",
    "DeploymentLatencyTracker",
    "DeploymentScheduler",
    "DeploymentSpec",
    "HeuristicCostModel",
    "JournalState",
    "LoadPredictor",
    "MeshConfig",
    "MeshPlan",
    "MeshPlanError",
    "MeshReplica",
    "plan_mesh",
    "NoHealthyReplicasError",
    "OutlierConfig",
    "Replica",
    "ReplicaState",
    "ReplicaUnavailableError",
    "RequestOptions",
    "RetryableTransportError",
    "RouterClosedError",
    "RouterCore",
    "RouterSaturatedError",
    "RoutingTablePublisher",
    "SchedulingConfig",
    "SLOConfig",
    "StaleEpochError",
    "StaleTableError",
    "SLOEngine",
    "ServeController",
    "StandaloneRouter",
    "remote_replica_resolver",
    "shared_object_resolver",
    "CompileCacheTier",
    "WarmPool",
    "WarmPoolConfig",
]
