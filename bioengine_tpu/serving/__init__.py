from bioengine_tpu.serving.batching import ContinuousBatcher
from bioengine_tpu.serving.controller import (
    DeploymentHandle,
    DeploymentSpec,
    ServeController,
)
from bioengine_tpu.serving.replica import Replica, ReplicaState

__all__ = [
    "ContinuousBatcher",
    "DeploymentHandle",
    "DeploymentSpec",
    "ServeController",
    "Replica",
    "ReplicaState",
]
