"""Shared compile-cache tier — the controller-side store.

A worker host's persistent XLA cache (utils/compile_cache.py) only
helps the machine that already paid the compile. At production churn
(autoscale-up onto a fresh node, a preempted TPU replaced by a new
lease) the new host's directory is empty and the replica pays the full
20-40 s compile before its first request — exactly the cold-start the
autoscaler was trying to get ahead of.

This store promotes the cache to a controller-coordinated tier: worker
hosts publish their locally-compiled entries here (``register_host``
join + after every replica start) and fetch what the fleet already
compiled before their first compile would happen. Entries are keyed
exactly as jax keys them on disk (``jit_<fn>-<hash>-cache``), so a
fetch-installed file IS a local persistent-cache hit — no re-keying,
no format translation. Bulk bytes ride the existing RPC data plane
(the PR 3 zero-copy transport moves them as out-of-band payloads).

Directory-backed and size-bounded: eviction is LRU on access time, so
the programs the fleet keeps re-fetching stay resident.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional

from bioengine_tpu.utils import flight, metrics
from bioengine_tpu.utils.compile_cache import _safe_entry_name

DEFAULT_TIER_DIR = "~/.cache/bioengine-tpu/xla-tier"

TIER_SERVED = metrics.counter(
    "compile_tier_served_total",
    "tier fetch requests served with an entry (tier hits)",
)
TIER_MISSES = metrics.counter(
    "compile_tier_miss_total",
    "tier fetch requests for entries the tier does not hold",
)
TIER_STORED = metrics.counter(
    "compile_tier_stored_total",
    "entries accepted into the tier from publishing hosts",
)
TIER_EVICTIONS = metrics.counter(
    "compile_tier_evictions_total",
    "entries evicted to keep the tier under its size bound",
)


class CompileCacheTier:
    """Bounded, directory-backed store of compiled-program cache
    entries, served over the serve-router verbs ``compile_cache_list``
    / ``compile_cache_fetch`` / ``compile_cache_publish``."""

    def __init__(
        self,
        directory: str | Path | None = None,
        max_bytes: Optional[int] = None,
    ):
        self.directory = Path(
            directory
            or os.environ.get("BIOENGINE_COMPILE_TIER_DIR")
            or DEFAULT_TIER_DIR
        ).expanduser()
        self.max_bytes = (
            int(max_bytes)
            if max_bytes is not None
            else int(
                float(os.environ.get("BIOENGINE_COMPILE_TIER_MAX_MB", "2048"))
                * 1024
                * 1024
            )
        )
        self._available: Optional[bool] = None
        # lifetime counters (the metrics above are process-global; an
        # operator asking THIS tier's hit rate reads stats())
        self.served = 0
        self.missed = 0
        self.stored = 0
        self.evicted = 0

    def _ensure_dir(self) -> bool:
        if self._available is None:
            try:
                self.directory.mkdir(parents=True, exist_ok=True)
                self._available = True
            except OSError:
                # verdict cached — a read-only controller FS degrades
                # the tier to "empty", it never breaks register_host
                self._available = False
        return self._available

    # ---- verbs --------------------------------------------------------------

    def list(self) -> dict[str, int]:
        """{entry_name: size_bytes} of everything the tier holds."""
        if not self._ensure_dir():
            return {}
        out: dict[str, int] = {}
        try:
            for p in self.directory.iterdir():
                if _safe_entry_name(p.name) and p.is_file():
                    out[p.name] = p.stat().st_size
        except OSError:
            return {}
        return out

    def fetch(self, name: str) -> Optional[bytes]:
        """One entry's bytes (touches its atime for LRU), or None."""
        if not self._ensure_dir() or not _safe_entry_name(name):
            self.missed += 1
            TIER_MISSES.inc()
            return None
        p = self.directory / name
        try:
            blob = p.read_bytes()
        except OSError:
            self.missed += 1
            TIER_MISSES.inc()
            return None
        try:
            now = time.time()
            os.utime(p, (now, now))
        except OSError:
            pass
        self.served += 1
        TIER_SERVED.inc()
        return blob

    def publish(self, name: str, blob: bytes) -> bool:
        """Accept one entry from a host. Idempotent (an existing entry
        is kept — every host compiling the same program publishes the
        same bytes); oversized single entries are refused outright."""
        if (
            not self._ensure_dir()
            or not _safe_entry_name(name)
            or not isinstance(blob, (bytes, bytearray, memoryview))
        ):
            return False
        blob = bytes(blob)
        if len(blob) > self.max_bytes:
            return False
        p = self.directory / name
        if p.exists():
            return False
        try:
            tmp = p.with_name(f".pub-{os.getpid()}-{name[:64]}")
            tmp.write_bytes(blob)
            os.replace(tmp, p)
        except OSError:
            return False
        self.stored += 1
        TIER_STORED.inc()
        flight.record(
            "program.cache_publish", entry=name[:120], bytes=len(blob)
        )
        self._evict_over_budget()
        return True

    def _evict_over_budget(self) -> None:
        entries = []
        total = 0
        try:
            for p in self.directory.iterdir():
                if _safe_entry_name(p.name) and p.is_file():
                    st = p.stat()
                    entries.append((st.st_atime, st.st_size, p))
                    total += st.st_size
        except OSError:
            return
        if total <= self.max_bytes:
            return
        for _, size, p in sorted(entries):  # oldest access first
            try:
                p.unlink()
            except OSError:
                continue
            self.evicted += 1
            TIER_EVICTIONS.inc()
            flight.record("program.cache_evict_tier", entry=p.name[:120])
            total -= size
            if total <= self.max_bytes:
                break

    # ---- status -------------------------------------------------------------

    def stats(self) -> dict:
        listing = self.list()
        requests = self.served + self.missed
        return {
            "directory": str(self.directory),
            "available": bool(self._ensure_dir()),
            "entries": len(listing),
            "bytes": sum(listing.values()),
            "max_bytes": self.max_bytes,
            "served": self.served,
            "missed": self.missed,
            "stored": self.stored,
            "evicted": self.evicted,
            "hit_rate": round(self.served / requests, 4) if requests else 0.0,
        }
