"""Typed error taxonomy for the fault-tolerant request path.

The request path must distinguish three failure classes, because each
gets different treatment by ``DeploymentHandle.call``:

- **Transport / placement** (:class:`RetryableTransportError` and the
  builtin ``ConnectionError`` family): the call may never have reached
  the application. Idempotent calls fail over to another healthy
  replica; non-idempotent calls surface the error exactly once, typed,
  so the caller KNOWS the outcome is ambiguous.
- **Application** (anything the deployment instance raised, locally or
  as a :class:`~bioengine_tpu.rpc.protocol.RemoteError`): the call ran
  and failed deterministically. Never retried — retrying would double
  side effects and hide real bugs.
- **Deadline** (:class:`DeadlineExceeded`): the request's time budget
  ran out. A per-attempt timeout is ambiguous like a transport error
  (retry only if idempotent and budget remains); an exhausted overall
  deadline is terminal.

Remote classification rides the wire via exception TYPE NAMES: the RPC
plane packs ``type(exc).__name__`` into ``RemoteError.type_name``
(rpc/protocol.py ``_pack_exception``), so a worker host raising
``ReplicaUnavailableError`` is recognized as retryable on the
controller side without any new wire fields.
"""

from __future__ import annotations

import asyncio
import enum

from bioengine_tpu.rpc.protocol import RemoteError


class RetryableTransportError(RuntimeError):
    """The call failed before/while crossing the transport or placement
    layer — the application may never have seen it. Safe to retry when
    the call is idempotent."""


class ReplicaUnavailableError(RetryableTransportError):
    """The targeted replica cannot take new calls (not healthy, gone
    from its host, or draining). A placement error: another replica may
    serve the same call."""


class NoHealthyReplicasError(RetryableTransportError):
    """No routable replica exists right now (restart window). Retryable
    because the health loop / provisioner may re-place one."""


class ApplicationError(Exception):
    """The deployment instance itself raised — deterministic, never
    retried. (Classification treats any unrecognized exception as
    application-level; this type exists for callers that want to raise
    an explicitly-final error through the retry layer.)"""


class StaleEpochError(RuntimeError):
    """A control verb carried a LOWER controller epoch than this host
    has already seen — the sender is a wedged-then-revived old
    controller that lost a crash/upgrade race. The verb is rejected
    (epoch fencing, the split-brain guard): deliberately NOT a
    transport error, because retrying the same stale verb can never
    succeed and failing it over would just spray the stale intent at
    another host. Classified APPLICATION both locally and over the
    wire (``RemoteError.type_name == "StaleEpochError"`` is not in the
    retryable set)."""

    def __init__(self, message: str, seen_epoch: int = 0,
                 got_epoch: int = 0):
        super().__init__(message)
        self.seen_epoch = seen_epoch
        self.got_epoch = got_epoch


class StaleTableError(StaleEpochError):
    """A routing-table push carried a LOWER journal epoch (or a lower
    version under the SAME epoch) than the router already holds — the
    publisher is a wedged-then-revived old controller, or the push was
    reordered in flight. Rejected typed so the stale table can never
    regress a router's newer view; inherits the non-retryable
    classification of :class:`StaleEpochError` (re-pushing the same
    stale table can never succeed)."""


class RouterClosedError(RetryableTransportError):
    """The standalone router this request landed on is shutting down
    (or was killed) and admits no new requests. Retryable by design:
    the routing tier is stateless-per-request, so the client's typed
    retry machinery fails the request over to a sibling router."""


class AdmissionRejectedError(RuntimeError):
    """The global scheduler shed this request at admission (queue depth
    over budget, tenant quota exhausted, or a deadline that could never
    be met). Deliberately NOT retryable: the rejection is the
    deployment-wide backpressure signal — retrying against the same
    saturated queue (or failing over to a sibling replica of the same
    deployment) cannot help; back off at the client instead.
    ``reason`` is one of ``queue_full`` / ``tenant_quota`` /
    ``deadline_infeasible``."""

    def __init__(self, message: str, reason: str = "queue_full"):
        super().__init__(message)
        self.reason = reason


class RouterSaturatedError(AdmissionRejectedError):
    """The standalone router this request landed on is at its inflight
    cap (``BIOENGINE_ROUTER_MAX_INFLIGHT``). Same non-retryable
    backpressure semantics as its parent — every sibling router shares
    the replica pool, so failing over would just move the overload —
    but typed so dashboards can tell router saturation apart from a
    scheduler queue rejection."""

    def __init__(self, message: str):
        super().__init__(message, reason="router_saturated")


class DeadlineExceeded(asyncio.TimeoutError):
    """The request's overall deadline expired (including any failover
    backoff)."""


class FailureKind(str, enum.Enum):
    TRANSPORT = "transport"
    APPLICATION = "application"
    DEADLINE = "deadline"


# Remote exception type names that indicate the failure happened in the
# transport/placement layer on the far side, not in application code.
_RETRYABLE_REMOTE_TYPES = frozenset(
    {
        "ConnectionError",
        "ConnectionResetError",
        "ConnectionAbortedError",
        "ConnectionRefusedError",
        "ConnectionLost",       # rpc.client: ws dropped with call in flight
        "BrokenPipeError",
        "FaultInjected",
        "RetryableTransportError",
        "ReplicaUnavailableError",
        "NoHealthyReplicasError",
    }
)


def classify_exception(exc: BaseException) -> FailureKind:
    """Map an exception from a replica call to its failure class."""
    if isinstance(exc, DeadlineExceeded):
        return FailureKind.DEADLINE
    if isinstance(exc, (ApplicationError, AdmissionRejectedError)):
        # admission rejection is terminal backpressure, not a transport
        # fault — the retry layer must surface it, never fail it over
        return FailureKind.APPLICATION
    if isinstance(exc, (RetryableTransportError, ConnectionError)):
        return FailureKind.TRANSPORT
    if isinstance(exc, (asyncio.TimeoutError, TimeoutError)):
        # a per-attempt timeout: outcome ambiguous, same retry rules as
        # a transport error (idempotent-only)
        return FailureKind.TRANSPORT
    if isinstance(exc, RemoteError):
        if exc.type_name in _RETRYABLE_REMOTE_TYPES:
            return FailureKind.TRANSPORT
        if exc.type_name == "TimeoutError":
            return FailureKind.TRANSPORT  # remote per-attempt timeout
        if exc.type_name == "KeyError" and "no replica" in str(exc):
            # the host dropped/never had the replica — placement moved
            return FailureKind.TRANSPORT
        return FailureKind.APPLICATION
    if isinstance(exc, OSError):
        return FailureKind.TRANSPORT
    return FailureKind.APPLICATION


def is_retryable(exc: BaseException) -> bool:
    return classify_exception(exc) is FailureKind.TRANSPORT


def is_caller_timeout(exc: BaseException) -> bool:
    """The CALLER's own time budget expired — locally
    (``asyncio.TimeoutError``, incl. :class:`DeadlineExceeded`) or
    enforced host-side and returned over the wire
    (``RemoteError('TimeoutError')``). Retry rules treat it like
    transport (outcome ambiguous), but it is NOT replica-health
    evidence: an impatient client must never feed the circuit breaker.
    This predicate is the ONE definition of that breaker discipline —
    router, scheduler fast path, and group dispatch all call it."""
    return isinstance(exc, asyncio.TimeoutError) or (
        isinstance(exc, RemoteError) and exc.type_name == "TimeoutError"
    )
