"""RemoteReplica — an RPC-backed replica living on a joined worker host.

Duck-types :class:`bioengine_tpu.serving.replica.Replica` so the
controller's deploy / health / routing paths treat local and remote
replicas identically — the analog of Ray Serve scheduling replica actors
onto SLURM-joined worker nodes (ref bioengine/apps/manager.py:355-455,
bioengine/cluster/slurm_workers.py:153-296). The instance is built ON
the host from a shipped artifact payload (manifest + sources + kwargs —
never pickled closures), so hosts need no shared filesystem.

Host death is detected three ways: the RPC server drops a host's
service the moment its websocket closes (so in-flight calls raise
``ConnectionError`` instead of timing out), ``check_health`` maps any
transport error to UNHEALTHY, and the controller's per-replica circuit
breaker ejects a replica after K consecutive transport failures
without waiting for the next health tick. A host that RECONNECTS
before its replicas are re-placed re-adopts them via
``serve-router.register_host`` reconciliation (warm weights and
compiled programs survive the blip).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Callable, Optional

from bioengine_tpu.serving.errors import ReplicaUnavailableError
from bioengine_tpu.serving.replica import (
    DEFAULT_DRAIN_TIMEOUT_S,
    ROUTABLE_STATES,
    ReplicaState,
    ReplicaStateMixin,
)
from bioengine_tpu.utils import flight, tracing


class RemoteReplica(ReplicaStateMixin):
    is_remote = True

    def __init__(
        self,
        app_id: str,
        deployment_name: str,
        host_id: str,
        host_service_id: str,
        call_host: Callable[..., Any],     # async (service_id, method, *args, **kw)
        payload: dict,
        device_ids: Optional[list[int]] = None,
        max_ongoing_requests: int = 10,
        log_sink: Optional[Callable[[str, str], None]] = None,
        drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
        stream_host: Optional[Callable[..., Any]] = None,  # async-gen (service_id, method, *args, **kw)
    ):
        self.app_id = app_id
        self.deployment_name = deployment_name
        self.replica_id = f"{deployment_name}-{uuid.uuid4().hex[:8]}"
        self.host_id = host_id
        self.host_service_id = host_service_id
        self.device_ids = device_ids or []
        self.max_ongoing_requests = max_ongoing_requests
        self.drain_timeout_s = drain_timeout_s
        self.state = ReplicaState.STARTING
        self.started_at = time.time()
        self._started_mono = time.monotonic()
        self.last_error: Optional[str] = None
        self._payload = payload
        self._call_host = call_host
        self._stream_host = stream_host
        self._ongoing = 0
        self._total_requests = 0
        self._idle_event = asyncio.Event()
        self._idle_event.set()
        self._log_sink = log_sink
        # controller-side TTFR view of a remote replica: coarse by
        # design (the host-side Replica owns the fine breakdown via its
        # own describe) — what promotion re-anchors is the span the
        # warm pool is accountable for
        self.ttfr: dict[str, Any] = {}
        self.promoted_from_warm_pool = False
        self._first_request_done = False

    def _log(self, line: str) -> None:
        if self._log_sink:
            self._log_sink(self.replica_id, line)

    # ---- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self._log(f"placing replica on host {self.host_id}")
        try:
            result = await self._call_host(
                self.host_service_id,
                "start_replica",
                replica_id=self.replica_id,
                device_ids=self.device_ids,
                max_ongoing_requests=self.max_ongoing_requests,
                payload=self._payload,
            )
            self.state = ReplicaState(result["state"])
            self.ttfr["init_seconds"] = round(
                time.monotonic() - self._started_mono, 4
            )
            self._log(f"remote replica started (state={self.state})")
        except Exception as e:
            self.last_error = str(e)[-2000:]
            self.state = ReplicaState.UNHEALTHY
            self._log(f"remote start failed: {e}")
            raise

    async def check_health(self) -> ReplicaState:
        if self.state in (
            ReplicaState.STOPPED,
            ReplicaState.UNHEALTHY,
            ReplicaState.DRAINING,
        ):
            return self.state
        try:
            result = await asyncio.wait_for(
                self._call_host(
                    self.host_service_id, "replica_health", self.replica_id
                ),
                timeout=30.0,
            )
            reported = ReplicaState(result["state"])
            # PROBATION is a CONTROLLER verdict the host-side replica
            # never hears about — a host reporting "healthy" is exactly
            # what gray failure looks like, so it must not clear the
            # soft ejection (latency evidence from probe traffic does);
            # any non-routable host-side state still wins
            if not (
                self.state == ReplicaState.PROBATION
                and reported in (ReplicaState.HEALTHY, ReplicaState.TESTING)
            ):
                self.state = reported
            if result.get("last_error"):
                self.last_error = result["last_error"]
        except Exception as e:
            # transport failure == host gone; the controller restarts us
            # elsewhere exactly like a crashed local replica
            self.last_error = f"host '{self.host_id}' unreachable: {e}"
            self.state = ReplicaState.UNHEALTHY
            self._log(self.last_error)
        return self.state

    async def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop routing new calls here; ask the host to finish what's
        in flight (bounded). Host-side drain failures are tolerated —
        a dead host has trivially drained."""
        if self.state in ROUTABLE_STATES + (ReplicaState.INITIALIZING,):
            self.state = ReplicaState.DRAINING
            self._log(f"draining ({self._ongoing} in-flight)")
            flight.record(
                "replica.drain",
                replica=self.replica_id,
                app=self.app_id,
                deployment=self.deployment_name,
                host=self.host_id,
                in_flight=self._ongoing,
            )
        timeout = self.drain_timeout_s if timeout_s is None else timeout_s
        started = time.monotonic()
        try:
            await asyncio.wait_for(
                self._call_host(
                    self.host_service_id,
                    "drain_replica",
                    self.replica_id,
                    timeout,
                ),
                timeout=timeout + 5.0,
            )
        except Exception as e:  # noqa: BLE001 — a dead host has trivially drained
            self._log(f"host-side drain failed (tolerated): {e}")
        # calls routed through THIS object (the only routing path) must
        # also settle before the replica is torn down — on whatever is
        # LEFT of the one drain budget, not a second full helping
        if self._ongoing == 0:
            return True
        remaining = max(0.0, timeout - (time.monotonic() - started))
        try:
            await asyncio.wait_for(self._idle_event.wait(), remaining)
            return True
        except asyncio.TimeoutError:
            self._log(f"drain timed out ({self._ongoing} stranded)")
            return False

    async def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        if self.state in (
            ReplicaState.HEALTHY,
            ReplicaState.TESTING,
            ReplicaState.PROBATION,
            ReplicaState.DRAINING,
        ):
            await self.drain(drain_timeout_s)
        self.state = ReplicaState.STOPPED
        try:
            await asyncio.wait_for(
                self._call_host(
                    self.host_service_id, "stop_replica", self.replica_id
                ),
                timeout=15.0,
            )
        except Exception as e:  # noqa: BLE001 — host already gone is stopped
            self._log(f"host-side stop failed (tolerated): {e}")
        self._log("remote replica stopped")

    # ---- request path -------------------------------------------------------

    async def call(self, method: str, *args, **kwargs) -> Any:
        return await self.call_bounded(method, args, kwargs)

    async def call_bounded(
        self,
        method: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Any:
        """Route one call to the host, propagating the remaining time
        budget so the HOST aborts the work too (not just the caller's
        await) when the deadline passes."""
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} not healthy ({self.state})"
            )
        self._ongoing += 1
        self._idle_event.clear()
        self._total_requests += 1
        try:
            extra: dict = {}
            if timeout_s is not None:
                # host enforces timeout_s around the instance call; the
                # transport timeout gets slack so the host's (typed)
                # TimeoutError wins the race over a bare client timeout
                extra = {"timeout_s": timeout_s, "rpc_timeout": timeout_s + 5.0}
            # the sampled trace context crosses to the host inside the
            # RPC envelope (server.call_service_method reads the
            # contextvar); this span is the controller-side view of the
            # whole remote hop (encode + wire + host-side work)
            with tracing.trace_span(
                "remote.call",
                replica=self.replica_id,
                host=self.host_id,
                method=method,
            ):
                result = await self._call_host(
                    self.host_service_id,
                    "replica_call",
                    self.replica_id,
                    method,
                    list(args),
                    kwargs or {},
                    **extra,
                )
            if not self._first_request_done:
                self._first_request_done = True
                self.ttfr["ttfr_seconds"] = round(
                    time.monotonic() - self._started_mono, 4
                )
                flight.record(
                    "replica.first_request",
                    replica=self.replica_id,
                    app=self.app_id,
                    deployment=self.deployment_name,
                    host=self.host_id,
                    method=method,
                    ttfr_seconds=self.ttfr["ttfr_seconds"],
                    warm_pool=self.promoted_from_warm_pool,
                )
            return result
        except KeyError as e:
            # a raw KeyError here is the ROUTER's (host service gone
            # from the registry, i.e. the websocket dropped) — app
            # exceptions always arrive wrapped as RemoteError
            raise ReplicaUnavailableError(
                f"host '{self.host_id}' service vanished: {e}"
            ) from e
        finally:
            self._ongoing -= 1
            if self._ongoing == 0:
                self._idle_event.set()

    async def call_stream(self, method: str, *args, **kwargs):
        """Streaming twin of :meth:`call`: routes to the host's
        ``replica_stream`` verb through the controller's
        ``call_service_stream`` bridge (``stream_host``), yielding each
        token frame as it lands. Transport failures mid-stream surface
        as ``ConnectionError`` — the handle's resume machinery turns
        them into an idempotent re-pick, never a silent truncation."""
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} not healthy ({self.state})"
            )
        if self._stream_host is None:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id}: control plane has no "
                f"streaming bridge (stream_host not wired)"
            )
        self._ongoing += 1
        self._idle_event.clear()
        self._total_requests += 1
        try:
            with tracing.trace_span(
                "remote.stream",
                replica=self.replica_id,
                host=self.host_id,
                method=method,
            ):
                agen = self._stream_host(
                    self.host_service_id,
                    "replica_stream",
                    self.replica_id,
                    method,
                    list(args),
                    kwargs or {},
                )
                first_seen = False
                async for item in agen:
                    if not first_seen:
                        first_seen = True
                        if not self._first_request_done:
                            self._first_request_done = True
                            self.ttfr["ttfr_seconds"] = round(
                                time.monotonic() - self._started_mono, 4
                            )
                    yield item
        except KeyError as e:
            raise ReplicaUnavailableError(
                f"host '{self.host_id}' service vanished: {e}"
            ) from e
        finally:
            self._ongoing -= 1
            if self._ongoing == 0:
                self._idle_event.set()

    async def call_batch(
        self,
        method: str,
        requests: list,
        timeout_s: Optional[float] = None,
    ) -> list:
        """A controller-coalesced group as ONE wire round trip: the
        ``__batch__`` verb carries all K member payloads in a single
        ``replica_call`` frame, the host fans them out through the
        replica's normal per-call path (where the instance's own
        batcher merges them into one forward), and K result envelopes
        ride back in one frame — K requests, one round trip."""
        if self.state not in ROUTABLE_STATES:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} not healthy ({self.state})"
            )
        n = len(requests)
        self._ongoing += n
        self._idle_event.clear()
        self._total_requests += n
        try:
            extra: dict = {}
            if timeout_s is not None:
                extra = {"timeout_s": timeout_s, "rpc_timeout": timeout_s + 5.0}
            with tracing.trace_span(
                "remote.call",
                replica=self.replica_id,
                host=self.host_id,
                method=method,
                batch=n,
            ):
                return await self._call_host(
                    self.host_service_id,
                    "replica_call",
                    self.replica_id,
                    "__batch__",
                    [method, requests],
                    {},
                    **extra,
                )
        except KeyError as e:
            raise ReplicaUnavailableError(
                f"host '{self.host_id}' service vanished: {e}"
            ) from e
        finally:
            self._ongoing -= n
            if self._ongoing == 0:
                self._idle_event.set()

    def mark_promoted(self) -> None:
        """Warm-pool standby → serving replica (see Replica.mark_promoted)."""
        self.promoted_from_warm_pool = True
        self.ttfr["standby_seconds"] = round(
            time.monotonic() - self._started_mono, 4
        )
        self._started_mono = time.monotonic()
        self._first_request_done = False

    @property
    def load(self) -> float:
        return self._ongoing / max(1, self.max_ongoing_requests)

    def describe(self) -> dict:
        cold = dict(self.ttfr)
        cold["promoted_from_warm_pool"] = self.promoted_from_warm_pool
        return {
            "replica_id": self.replica_id,
            "deployment": self.deployment_name,
            "state": self.state.value,
            "device_ids": self.device_ids,
            "host_id": self.host_id,
            "ongoing_requests": self._ongoing,
            # no queued_requests key: the semaphore queue lives in the
            # host-side Replica (visible via the host's get_status /
            # describe) — reporting 0 here would fake an idle queue and
            # the controller rollup treats a missing key as unknown
            "total_requests": self._total_requests,
            "load": self.load,
            "cold_start": cold,
            "uptime_seconds": time.monotonic() - self._started_mono,
            "last_error": self.last_error,
        }
