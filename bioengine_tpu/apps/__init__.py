from bioengine_tpu.apps.artifacts import LocalArtifactStore
from bioengine_tpu.apps.builder import AppBuilder, BuiltApp
from bioengine_tpu.apps.manager import AppsManager
from bioengine_tpu.apps.manifest import AppManifest, load_manifest, validate_manifest

__all__ = [
    "LocalArtifactStore",
    "AppBuilder",
    "BuiltApp",
    "AppsManager",
    "AppManifest",
    "load_manifest",
    "validate_manifest",
]
