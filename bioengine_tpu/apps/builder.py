"""AppBuilder — turn an artifact (manifest + deployment .py files) into
deployable specs.

Functional parity with the reference's builder (ref bioengine/apps/
builder.py): download each deployment file, ``exec`` it in a controlled
namespace with env vars applied (:1089-1246), introspect and validate
``__init__`` kwargs (:892-1087), compose multi-deployment apps by
binding handles to parameters named after sibling file stems
(:1474-1508), attach the datasets client (:657-661), isolate a per-app
working directory (:532-667), and resolve authorized users
(override > manifest, + admins) (:1522-1569).

TPU-native differences: no Ray runtime_env/venv machinery — apps run in
the worker image's environment (deps are declared, validated present,
not installed per-deploy), and each deployment's resource request is a
chip count + optional mesh spec instead of ``num_gpus``.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import sys
from pathlib import Path
from typing import Any, Callable, Optional

from bioengine_tpu.apps.artifacts import LocalArtifactStore
from bioengine_tpu.apps.manifest import AppManifest, load_manifest
from bioengine_tpu.rpc.schema import is_schema_method
from bioengine_tpu.serving.controller import DeploymentSpec
from bioengine_tpu.serving.mesh_plan import MeshConfig
from bioengine_tpu.serving.scheduler import SchedulingConfig
from bioengine_tpu.serving.slo import SLOConfig
from bioengine_tpu.serving.warm_pool import WarmPoolConfig
from bioengine_tpu.utils.logger import create_logger

# env var override mirroring the reference's local-artifact escape hatch
LOCAL_ARTIFACT_ENV = "BIOENGINE_LOCAL_ARTIFACT_PATH"


class AppBuildError(RuntimeError):
    pass


@dataclasses.dataclass
class BuiltApp:
    app_id: str
    manifest: AppManifest
    specs: list[DeploymentSpec]
    entry_name: str
    schema_methods: dict[str, dict]        # entry method name -> schema
    authorized_users: list[str]
    app_dir: Optional[Path] = None


class AppBuilder:
    def __init__(
        self,
        store: Optional[LocalArtifactStore] = None,
        workdir_root: str | Path = "~/.bioengine/apps",
        data_client_factory: Optional[Callable[[], Any]] = None,
        admin_users: Optional[list[str]] = None,
        log_file: Optional[str] = None,
    ):
        self.store = store
        self.workdir_root = Path(workdir_root).expanduser()
        self.data_client_factory = data_client_factory
        self.admin_users = list(admin_users or [])
        self.logger = create_logger("apps.builder", log_file=log_file)

    # ---- source loading -----------------------------------------------------

    def _stage_frontend(
        self,
        app_dir: Path,
        artifact_id: Optional[str],
        version: Optional[str],
        local_path: Optional[str | Path],
    ) -> None:
        """Copy the app's ``frontend/`` dir (if any) into the workdir so
        the manager can serve it as a static site (the reference hosts
        app frontends via Hypha's artifact static-site URL, ref
        bioengine/utils/artifact_utils.py:612-628; here the framework's
        own server does)."""
        import shutil

        # always drop the previous deploy's copy: app_dir is reused per
        # app_id, and a stale frontend must not survive an update that
        # removed or renamed files
        target = app_dir / "frontend"
        shutil.rmtree(target, ignore_errors=True)
        if local_path is not None:
            src = Path(local_path) / "frontend"
            if src.is_dir():
                shutil.copytree(src, target)
            return
        if self.store is None or artifact_id is None:
            return
        for rel in self.store.list_files(artifact_id, version):
            if not rel.startswith("frontend/"):
                continue
            out = target.parent / rel
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_bytes(self.store.get_file(artifact_id, rel, version))

    def _load_sources(
        self,
        artifact_id: Optional[str],
        version: Optional[str],
        local_path: Optional[str | Path],
    ) -> tuple[AppManifest, dict[str, str], dict[str, str]]:
        """Returns (manifest, {file_stem: source}, {sibling_stem: source}).

        Siblings are top-level ``*.py`` files of the artifact that are
        not deployment entries — apps import them as plain modules
        (``from normalizer import ...``), matching the reference where
        the whole app dir is the Ray runtime_env workdir."""
        local_override = os.environ.get(LOCAL_ARTIFACT_ENV)
        if local_path is None and local_override and artifact_id:
            candidate = Path(local_override) / artifact_id
            if candidate.exists():
                local_path = candidate
        if local_path is not None:
            base = Path(local_path)
            manifest = load_manifest(base)
            sources = {
                ref.file_stem: (base / ref.python_file).read_text()
                for ref in manifest.deployments
            }
            siblings = {
                p.stem: p.read_text()
                for p in sorted(base.glob("*.py"))
                if p.stem not in sources
            }
            return manifest, sources, siblings
        if self.store is None or artifact_id is None:
            raise AppBuildError(
                "need a local_path or an artifact store + artifact_id"
            )
        manifest = self.store.get_manifest(artifact_id, version)
        sources = {
            ref.file_stem: self.store.get_file(
                artifact_id, ref.python_file, version
            ).decode()
            for ref in manifest.deployments
        }
        siblings = {}
        for path in self.store.list_files(artifact_id, version):
            if "/" in path or not path.endswith(".py"):
                continue
            stem = path[: -len(".py")]
            if stem not in sources:
                siblings[stem] = self.store.get_file(
                    artifact_id, path, version
                ).decode()
        return manifest, sources, siblings

    def _install_sibling_modules(
        self, app_id: str, siblings: dict[str, str]
    ) -> None:
        """Exec sibling modules and register them in sys.modules under
        both a namespaced key and the bare stem, so deployment code can
        ``import normalizer`` at top level or lazily inside methods.

        Replicas share this process, so a bare stem already claimed by a
        DIFFERENT app is re-pointed at this app's module with a warning
        — the per-app namespaced key stays unambiguous either way."""
        import types

        # Pre-register every sibling before exec'ing any, so siblings can
        # import each other at top level regardless of file order (and
        # circular imports behave like normal partially-initialized
        # modules).
        modules: dict[str, types.ModuleType] = {}
        for stem in siblings:
            module = types.ModuleType(stem)
            module.__file__ = f"{stem}.py"
            module.__bioengine_app__ = app_id
            modules[stem] = module
            existing = sys.modules.get(stem)
            if existing is not None and existing is not module:
                owner = getattr(existing, "__bioengine_app__", None)
                if owner is None:
                    self.logger.warning(
                        "app '%s' module '%s' shadows an already-imported "
                        "module of the same name for this process",
                        app_id, stem,
                    )
                elif owner != app_id:
                    self.logger.warning(
                        "app module name '%s' already claimed by app "
                        "'%s'; re-pointing at app '%s'",
                        stem, owner, app_id,
                    )
            sys.modules[f"bioengine_app_{app_id}.{stem}"] = module
            sys.modules[stem] = module
        for stem, source in siblings.items():
            try:
                exec(
                    compile(source, f"{stem}.py", "exec"),
                    modules[stem].__dict__,
                )
            except Exception as e:
                raise AppBuildError(
                    f"executing app module '{stem}.py' failed: {e}"
                ) from e

    # ---- exec + class extraction --------------------------------------------

    def _load_class(
        self,
        stem: str,
        class_name: str,
        source: str,
        env_vars: dict[str, str],
        app_id: str,
    ) -> type:
        """Execute the deployment module and pull out the class.

        Env vars are applied to os.environ before exec (the reference
        passes them as exec globals AND runtime_env env_vars; one pinned
        process here, so os.environ is the single source). ``_``-prefixed
        keys are the secret convention — values masked in any status
        output (ref apps/manager.py:619-651)."""
        for k, v in env_vars.items():
            os.environ[k] = str(v)
        namespace: dict[str, Any] = {
            "__name__": f"bioengine_app_{app_id}_{stem}",
            "__file__": f"{stem}.py",
        }
        try:
            exec(compile(source, f"{stem}.py", "exec"), namespace)
        except Exception as e:
            raise AppBuildError(
                f"executing deployment '{stem}.py' failed: {e}"
            ) from e
        cls = namespace.get(class_name)
        if not inspect.isclass(cls):
            raise AppBuildError(
                f"'{stem}.py' does not define class '{class_name}'"
            )
        return cls

    # ---- kwargs validation --------------------------------------------------

    def _check_params(
        self,
        cls: type,
        kwargs: dict[str, Any],
        handle_params: set[str],
    ) -> None:
        """Validate provided kwargs against __init__'s signature —
        unexpected kwargs and missing required params fail the build,
        not the replica (ref builder.py:892-1087)."""
        sig = inspect.signature(cls.__init__)
        params = {n: p for n, p in sig.parameters.items() if n != "self"}
        accepts_var_kw = any(
            p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
        for name in kwargs:
            if name not in params and not accepts_var_kw:
                raise AppBuildError(
                    f"{cls.__name__}.__init__ got unexpected kwarg "
                    f"'{name}' (accepts: {sorted(params)})"
                )
        missing = [
            n
            for n, p in params.items()
            if p.default is inspect.Parameter.empty
            and p.kind
            in (
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
                inspect.Parameter.KEYWORD_ONLY,
            )
            and n not in kwargs
            and n not in handle_params
        ]
        if missing:
            raise AppBuildError(
                f"{cls.__name__}.__init__ missing required kwargs: {missing}"
            )

    # ---- build --------------------------------------------------------------

    def build(
        self,
        app_id: str,
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
        local_path: Optional[str | Path] = None,
        deployment_kwargs: Optional[dict[str, dict[str, Any]]] = None,
        env_vars: Optional[dict[str, str]] = None,
        authorized_users_override: Optional[list[str]] = None,
        make_handle: Optional[Callable[[str], Any]] = None,
        deployer: Optional[str] = None,
    ) -> BuiltApp:
        manifest, sources, siblings = self._load_sources(
            artifact_id, version, local_path
        )
        self._install_sibling_modules(app_id, siblings)
        deployment_kwargs = dict(deployment_kwargs or {})
        env_vars = dict(env_vars or {})

        app_dir = self.workdir_root / app_id
        app_dir.mkdir(parents=True, exist_ok=True)
        self._stage_frontend(app_dir, artifact_id, version, local_path)

        stems = [ref.file_stem for ref in manifest.deployments]
        classes: dict[str, type] = {}
        for ref in manifest.deployments:
            classes[ref.file_stem] = self._load_class(
                ref.file_stem,
                ref.class_name,
                sources[ref.file_stem],
                env_vars,
                app_id,
            )

        # artifact payload for remote placement: the complete app as
        # files + the original deploy kwargs, so a worker host rebuilds
        # the instance from source (never pickled closures) — the analog
        # of the reference's runtime_env workdir shipped to worker nodes
        import yaml as _yaml

        payload_files = {"manifest.yaml": _yaml.safe_dump(manifest.raw)}
        for ref in manifest.deployments:
            payload_files[ref.python_file] = sources[ref.file_stem]
        for stem, src in siblings.items():
            payload_files[f"{stem}.py"] = src
        base_payload = {
            "app_id": app_id,
            "files": payload_files,
            "deployment_kwargs": deployment_kwargs,
            "env_vars": env_vars,
        }

        specs: list[DeploymentSpec] = []
        entry_ref = manifest.entry_deployment
        for ref in manifest.deployments:
            cls = classes[ref.file_stem]
            kwargs = dict(deployment_kwargs.get(ref.file_stem, {}))
            sig_params = set(
                inspect.signature(cls.__init__).parameters
            ) - {"self"}
            # composition: parameters named after sibling stems get handles
            handle_params = {
                p for p in sig_params if p in stems and p != ref.file_stem
            }
            self._check_params(cls, kwargs, handle_params)
            cfg = manifest.deployment_config.get(ref.file_stem, {})
            factory = self._make_factory(
                cls, kwargs, handle_params, make_handle, app_dir
            )
            # operator-facing batching knobs (manifest
            # deployment_config.<dep>.batching) ride the spec so
            # replicas — local or rebuilt from the shipped payload on a
            # worker host — tune their ContinuousBatcher without code
            # changes; scheduling opts the deployment into the global
            # scheduler (cross-replica batching, admission control,
            # predictive autoscaling)
            batching = dict(cfg.get("batching") or {})
            scheduling_cfg = cfg.get("scheduling")
            slo_cfg = cfg.get("slo")
            warm_pool_cfg = cfg.get("warm_pool")
            mesh_cfg = cfg.get("mesh")
            try:
                spec_max_batch = (
                    int(batching["max_batch"])
                    if "max_batch" in batching
                    else None
                )
                spec_max_wait_ms = (
                    float(batching["max_wait_ms"])
                    if "max_wait_ms" in batching
                    else None
                )
                scheduling = (
                    SchedulingConfig.from_config(dict(scheduling_cfg))
                    if scheduling_cfg
                    else None
                )
                slo = (
                    SLOConfig.from_config(dict(slo_cfg)) if slo_cfg else None
                )
                warm_pool = (
                    WarmPoolConfig.from_config(dict(warm_pool_cfg))
                    if warm_pool_cfg
                    else None
                )
                mesh = (
                    MeshConfig.from_config(dict(mesh_cfg))
                    if mesh_cfg
                    else None
                )
                if mesh is not None and warm_pool is not None:
                    # a mesh standby's chips span hosts, so the pool's
                    # per-host skip_hosts guard cannot protect its
                    # promotion — reject the combo instead of promoting
                    # a dead-sharded mesh into rotation
                    raise ValueError(
                        "warm_pool cannot combine with mesh "
                        "(standby promotion is per-host; a mesh spans "
                        "several) — drop one of the two blocks"
                    )
            except (TypeError, ValueError) as e:
                # every config mistake on this path fails TYPED with the
                # deployment named — never a raw traceback
                raise AppBuildError(
                    f"invalid mesh/batching/scheduling/warm_pool/slo "
                    f"config for deployment '{ref.file_stem}': {e} "
                    f"(vocabulary reference: docs/apps-guide.md, "
                    f"'The deployment_config vocabulary')"
                ) from e
            specs.append(
                DeploymentSpec(
                    name=ref.file_stem,
                    instance_factory=factory,
                    num_replicas=int(cfg.get("num_replicas", 1)),
                    min_replicas=int(cfg.get("min_replicas", 1)),
                    max_replicas=int(cfg.get("max_replicas", 3)),
                    chips_per_replica=int(cfg.get("chips", 0)),
                    max_ongoing_requests=int(cfg.get("max_ongoing_requests", 10)),
                    autoscale=bool(cfg.get("autoscale", True)),
                    max_batch=spec_max_batch,
                    max_wait_ms=spec_max_wait_ms,
                    scheduling=scheduling,
                    slo=slo,
                    warm_pool=warm_pool,
                    mesh=mesh,
                    remote_payload={
                        **base_payload,
                        "deployment": ref.file_stem,
                    },
                )
            )

        entry_cls = classes[entry_ref.file_stem]
        schema_methods = {
            name: fn.__schema__
            for name, fn in inspect.getmembers(entry_cls, callable)
            if is_schema_method(fn)
        }
        if not schema_methods:
            raise AppBuildError(
                f"entry class {entry_cls.__name__} exposes no "
                f"@schema_method endpoints"
            )

        # authorized users: explicit override beats manifest; admins and
        # the deployer always included (ref builder.py:1522-1569)
        users = list(
            authorized_users_override
            if authorized_users_override is not None
            else manifest.authorized_users
        )
        for extra in [*self.admin_users, deployer]:
            if extra and extra not in users:
                users.append(extra)
        if not users:
            users = list(self.admin_users)

        # deploy entry LAST so its siblings exist first
        specs_sorted = [s for s in specs if s.name != entry_ref.file_stem] + [
            s for s in specs if s.name == entry_ref.file_stem
        ]
        return BuiltApp(
            app_id=app_id,
            manifest=manifest,
            specs=specs_sorted,
            entry_name=entry_ref.file_stem,
            schema_methods=schema_methods,
            authorized_users=users,
            app_dir=app_dir,
        )

    def _make_factory(
        self,
        cls: type,
        kwargs: dict[str, Any],
        handle_params: set[str],
        make_handle: Optional[Callable[[str], Any]],
        app_dir: Path,
    ) -> Callable[[], Any]:
        data_factory = self.data_client_factory

        def factory():
            call_kwargs = dict(kwargs)
            for p in handle_params:
                if make_handle is None:
                    raise AppBuildError(
                        f"deployment needs a handle for '{p}' but no "
                        f"handle provider was configured"
                    )
                call_kwargs[p] = make_handle(p)
            instance = cls(**call_kwargs)
            # per-app scratch dir + datasets client attach
            instance.workdir = app_dir
            if data_factory is not None and not hasattr(
                instance, "bioengine_datasets"
            ):
                instance.bioengine_datasets = data_factory()
            return instance

        factory.__name__ = f"factory_{cls.__name__}"
        return factory
