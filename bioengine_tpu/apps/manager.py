"""AppsManager — the deploy/update/undeploy lifecycle owner.

Parity surface with the reference's AppsManager (ref bioengine/apps/
manager.py): ``deploy_app`` under a deployment lock with generated
two-word app ids (:203-237), resource-fit pre-check with scale-out
allowance (:239-353), ``stop_app``/``stop_all_apps``, artifact CRUD
(``upload_app``/``list_apps``/``get_app_manifest``/``delete_app``,
:1073-1467), app-dir listing/cleanup (:1184-1304), status aggregation
with per-replica (incl. dead) logs and masked ``_``-secret env keys
(:560-773), auto-redeploy monitoring (:1003-1071), and startup apps
(:937-1001).
"""

from __future__ import annotations

import asyncio
import random
import shutil
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from bioengine_tpu.apps.artifacts import LocalArtifactStore
from bioengine_tpu.apps.builder import AppBuilder, BuiltApp
from bioengine_tpu.apps.proxy import AppServiceProxy
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving.controller import DeploymentHandle, ServeController
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.permissions import check_permissions, create_context

_ADJECTIVES = (
    "amber", "brisk", "calm", "deft", "eager", "fuzzy", "gold", "hazy",
    "icy", "jolly", "keen", "lucid", "mellow", "noble", "opal", "prime",
    "quiet", "rapid", "solar", "tidal", "umber", "vivid", "warm", "zesty",
)
_NOUNS = (
    "axon", "basil", "comet", "delta", "ember", "fjord", "glade", "harbor",
    "iris", "jade", "krill", "lotus", "meadow", "nectar", "orchid", "pine",
    "quartz", "reef", "sprout", "thistle", "urchin", "vortex", "willow", "zephyr",
)


@dataclass
class AppRecord:
    app_id: str
    built: BuiltApp
    proxy: AppServiceProxy
    artifact_id: Optional[str]
    version: Optional[str]
    local_path: Optional[str]
    deployed_by: str
    deployed_at: float = field(default_factory=time.time)
    auto_redeploy: bool = False
    env_keys: list[str] = field(default_factory=list)
    deployment_kwargs: dict = field(default_factory=dict)
    # stored verbatim so auto-redeploy reproduces the ORIGINAL deploy
    # call — without these, a restart would silently fall back to the
    # manifest's ACL and lose env overrides
    authorized_users: Optional[list[str]] = None
    env_vars: dict = field(default_factory=dict)
    redeploy_count: int = 0
    frontend_url: Optional[str] = None


class AppsManager:
    def __init__(
        self,
        controller: ServeController,
        server: RpcServer,
        store: Optional[LocalArtifactStore] = None,
        builder: Optional[AppBuilder] = None,
        admin_users: Optional[list[str]] = None,
        can_scale_out: bool = False,
        max_auto_redeploys: int = 3,
        state_file: Optional[str | Path] = None,
        log_file: Optional[str] = None,
    ):
        self.controller = controller
        self.server = server
        self.store = store
        self.builder = builder or AppBuilder(
            store=store, admin_users=admin_users
        )
        self.admin_users = list(admin_users or [])
        self.can_scale_out = can_scale_out
        self.max_auto_redeploys = max_auto_redeploys
        self.state_file = Path(state_file) if state_file else None
        self.records: dict[str, AppRecord] = {}
        self.logger = create_logger("apps.manager", log_file=log_file)
        self._deploy_lock = asyncio.Lock()

    # ---- record persistence + restart recovery -------------------------------

    def _save_records(self) -> None:
        """Persist every deploy's reproducible arguments so a restarted
        worker can re-adopt its apps (ref bioengine/apps/manager.py:
        841-935 recovers running Serve apps after a worker crash; here
        recovery is redeploy-from-record, since replicas die with the
        worker process)."""
        if self.state_file is None:
            return
        payload = [
            {
                "app_id": r.app_id,
                "artifact_id": r.artifact_id,
                "version": r.version,
                "local_path": r.local_path,
                "deployment_kwargs": r.deployment_kwargs,
                "env_vars": r.env_vars,
                "authorized_users": r.authorized_users,
                "auto_redeploy": r.auto_redeploy,
                "deployed_by": r.deployed_by,
                "deployed_at": r.deployed_at,
            }
            for r in self.records.values()
        ]
        import json

        self.state_file.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.state_file.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(payload, indent=2))
        tmp.rename(self.state_file)

    async def recover_deployed_applications(self) -> list[dict]:
        """Redeploy every app recorded by a previous worker life. Never
        raises — a single bad record must not block worker startup."""
        if self.state_file is None or not self.state_file.exists():
            return []
        import json

        try:
            saved = json.loads(self.state_file.read_text())
        except (OSError, json.JSONDecodeError) as e:
            self.logger.error(f"unreadable app state file: {e}")
            return []
        admin_ctx = create_context(
            self.admin_users[0] if self.admin_users else "system",
            workspace="bioengine",
        )
        results = []
        for rec in saved:
            app_id = rec.get("app_id")
            if app_id in self.records:
                continue
            try:
                results.append(
                    await self.deploy_app(
                        artifact_id=rec.get("artifact_id"),
                        version=rec.get("version"),
                        local_path=rec.get("local_path"),
                        app_id=app_id,
                        deployment_kwargs=rec.get("deployment_kwargs"),
                        env_vars=rec.get("env_vars"),
                        authorized_users=rec.get("authorized_users"),
                        auto_redeploy=rec.get("auto_redeploy", False),
                        context=admin_ctx,
                    )
                )
                self.logger.info(f"recovered app '{app_id}'")
            except Exception as e:
                self.logger.error(f"recovery of '{app_id}' failed: {e}")
        return results

    # ---- id generation ------------------------------------------------------

    def _generate_app_id(self) -> str:
        for _ in range(100):
            app_id = (
                f"{random.choice(_ADJECTIVES)}-{random.choice(_NOUNS)}"
            )
            if app_id not in self.records:
                return app_id
        return f"app-{random.getrandbits(32):08x}"

    # ---- resource pre-check -------------------------------------------------

    def _check_resources(self, built: BuiltApp) -> None:
        """Fail fast when the app can never fit; in scalable modes a
        shortfall is allowed (the provisioner will add capacity), same
        allowance as ref manager.py:239-353."""
        needed = sum(
            s.chips_per_replica * s.num_replicas for s in built.specs
        )
        total = self.controller.cluster_state.topology.n_chips
        free = self.controller.cluster_state.free_chips()
        if needed > free and not self.can_scale_out:
            raise RuntimeError(
                f"app needs {needed} chips, only {free}/{total} free and "
                f"this cluster mode cannot scale out"
            )

    # ---- deploy / stop ------------------------------------------------------

    async def deploy_app(
        self,
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
        local_path: Optional[str] = None,
        app_id: Optional[str] = None,
        deployment_kwargs: Optional[dict] = None,
        env_vars: Optional[dict] = None,
        authorized_users: Optional[list[str]] = None,
        auto_redeploy: bool = False,
        context: Optional[dict] = None,
    ) -> dict:
        check_permissions(context, self.admin_users, "deploy_app")
        from bioengine_tpu.utils.tracing import span

        with span("deploy_app", app_id=app_id, artifact_id=artifact_id):
            return await self._deploy_app_inner(
                artifact_id, version, local_path, app_id,
                deployment_kwargs, env_vars, authorized_users,
                auto_redeploy, context,
            )

    async def _deploy_app_inner(
        self,
        artifact_id,
        version,
        local_path,
        app_id,
        deployment_kwargs,
        env_vars,
        authorized_users,
        auto_redeploy,
        context,
    ) -> dict:
        async with self._deploy_lock:
            is_update = app_id is not None and app_id in self.records
            if is_update:
                await self._undeploy(app_id)
            app_id = app_id or self._generate_app_id()
            deployer = (context or {}).get("user", {}).get("id", "unknown")

            # build in a thread: it execs sources and (with a REMOTE
            # artifact store) does blocking HTTP fetches that must not
            # stall the event loop serving those very requests
            built = await asyncio.to_thread(
                self.builder.build,
                app_id=app_id,
                artifact_id=artifact_id,
                version=version,
                local_path=local_path,
                deployment_kwargs=deployment_kwargs,
                env_vars=env_vars,
                authorized_users_override=authorized_users,
                make_handle=lambda name, a=app_id: DeploymentHandle(
                    self.controller, a, name
                ),
                deployer=deployer,
            )
            # journal recovery may have resurrected the controller half
            # of this app already (worker restart with a control dir:
            # the journal AND the manager's record file cover the same
            # apps) — re-attach the build to the recovered intent
            # instead of colliding with an "already deployed" error.
            # The resource pre-check is skipped on that path: adopted
            # replicas already hold their chips.
            if not self.controller.adopt_recovered_specs(
                app_id, built.specs, acl=built.authorized_users
            ):
                self._check_resources(built)
                await self.controller.deploy(
                    app_id, built.specs, acl=built.authorized_users
                )
            proxy = AppServiceProxy(self.server, self.controller, built)
            proxy.register()
            frontend_url = self._register_frontend(app_id, built)
            self.records[app_id] = AppRecord(
                app_id=app_id,
                built=built,
                proxy=proxy,
                artifact_id=artifact_id,
                version=version,
                local_path=str(local_path) if local_path else None,
                deployed_by=deployer,
                auto_redeploy=auto_redeploy,
                env_keys=sorted(env_vars or {}),
                deployment_kwargs=dict(deployment_kwargs or {}),
                authorized_users=(
                    list(authorized_users) if authorized_users is not None else None
                ),
                env_vars=dict(env_vars or {}),
                frontend_url=frontend_url,
            )
            await asyncio.to_thread(self._save_records)
            self.logger.info(
                f"deployed '{app_id}' ({built.manifest.name}) "
                f"by {deployer}"
            )
            return {
                "app_id": app_id,
                "service_id": proxy.service_id,
                "name": built.manifest.name,
                "methods": sorted(built.schema_methods),
                "frontend_url": frontend_url,
            }

    def _register_frontend(self, app_id: str, built) -> Optional[str]:
        """Serve the app's ``frontend/`` dir (if any) through the RPC
        server's static route — the analog of the reference's
        artifact static-site URL (ref bioengine/apps/manager.py uses
        Hypha's site hosting; here the framework serves it itself)."""
        if built.app_dir is None:
            return None
        frontend = Path(built.app_dir) / "frontend"
        if not frontend.is_dir():
            return None
        register = getattr(self.server, "register_static_dir", None)
        if register is None:
            return None
        return register(app_id, frontend)

    async def _undeploy(self, app_id: str) -> None:
        record = self.records.pop(app_id, None)
        if record is None:
            return
        unregister = getattr(self.server, "unregister_static_dir", None)
        if unregister is not None:
            unregister(app_id)
        record.proxy.deregister()
        await self.controller.undeploy(app_id)
        await asyncio.to_thread(self._save_records)

    async def stop_app(self, app_id: str, context: Optional[dict] = None) -> dict:
        check_permissions(context, self.admin_users, "stop_app")
        if app_id not in self.records:
            raise KeyError(f"app '{app_id}' is not deployed")
        async with self._deploy_lock:
            await self._undeploy(app_id)
        return {"app_id": app_id, "status": "STOPPED"}

    async def stop_all_apps(
        self, context: Optional[dict] = None, forget: bool = True
    ) -> list[str]:
        """``forget=False`` (worker shutdown) keeps the persisted records
        so the next worker life re-adopts the apps; ``forget=True`` (an
        admin explicitly clearing the cluster) erases them."""
        check_permissions(context, self.admin_users, "stop_all_apps")
        async with self._deploy_lock:
            keep = (
                self.state_file.read_text()
                if not forget and self.state_file and self.state_file.exists()
                else None
            )
            stopped = list(self.records)
            for app_id in stopped:
                await self._undeploy(app_id)
            if keep is not None:
                self.state_file.write_text(keep)
        return stopped

    # ---- status -------------------------------------------------------------

    def get_app_status(
        self, app_id: Optional[str] = None, context: Optional[dict] = None
    ) -> dict:
        if app_id is not None:
            return self._one_status(app_id)
        return {aid: self._one_status(aid) for aid in self.records}

    def _one_status(self, app_id: str) -> dict:
        record = self.records.get(app_id)
        if record is None:
            raise KeyError(f"app '{app_id}' is not deployed")
        status = self.controller.get_app_status(app_id)
        status.update(
            {
                "name": record.built.manifest.name,
                "id_emoji": record.built.manifest.id_emoji,
                "artifact_id": record.artifact_id,
                "version": record.version,
                "deployed_by": record.deployed_by,
                "deployed_at": record.deployed_at,
                "service_id": record.proxy.service_id,
                "frontend_url": record.frontend_url,
                "mcp_url": record.proxy.mcp_url,
                "rtc_service_id": record.proxy.rtc_service_id,
                # public static-site URL when deployed from an artifact
                # (ref utils/artifact_utils.py:612-628)
                "artifact_view_url": (
                    f"{self.server.http_url}/artifacts/{record.artifact_id}/view/"
                    if record.artifact_id
                    and getattr(self.server, "http_url", None)
                    and getattr(self.server, "artifact_service", None)
                    else None
                ),
                "available_methods": sorted(record.built.schema_methods),
                "authorized_users": record.built.authorized_users,
                # secret convention: only names, never values
                "env_keys": [
                    k if not k.startswith("_") else f"{k} (masked)"
                    for k in record.env_keys
                ],
                "auto_redeploy": record.auto_redeploy,
                "replica_logs": self.controller.cluster_state.get_replica_logs(
                    app_id
                ),
            }
        )
        return status

    def list_apps(self, context: Optional[dict] = None) -> list[dict]:
        check_permissions(context, self.admin_users, "list_apps")
        if self.store is None:
            return []
        out = []
        for artifact_id in self.store.list_artifacts():
            manifest = self.store.get_manifest(artifact_id)
            out.append(
                {
                    "artifact_id": artifact_id,
                    "name": manifest.name,
                    "description": manifest.description,
                    "versions": self.store.versions(artifact_id),
                    "latest": self.store.latest_version(artifact_id),
                }
            )
        return out

    # ---- artifact CRUD ------------------------------------------------------

    def upload_app(
        self,
        src_dir: Optional[str] = None,
        files: Optional[dict] = None,
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
        context: Optional[dict] = None,
    ) -> dict:
        """Upload from a worker-local directory OR an in-memory file
        mapping (what remote CLI clients send — their filesystem is not
        visible here)."""
        check_permissions(context, self.admin_users, "upload_app")
        if self.store is None:
            raise RuntimeError("no artifact store configured")
        if (src_dir is None) == (files is None):
            raise ValueError("provide exactly one of src_dir or files")
        if files is not None:
            aid, ver = self.store.put_files(files, artifact_id, version)
        else:
            aid, ver = self.store.put(src_dir, artifact_id, version)
        return {"artifact_id": aid, "version": ver}

    def get_app_manifest(
        self,
        artifact_id: str,
        version: Optional[str] = None,
        context: Optional[dict] = None,
    ) -> dict:
        check_permissions(context, self.admin_users, "get_app_manifest")
        if self.store is None:
            raise RuntimeError("no artifact store configured")
        return self.store.get_manifest(artifact_id, version).raw

    def delete_app(
        self,
        artifact_id: str,
        version: Optional[str] = None,
        context: Optional[dict] = None,
    ) -> dict:
        check_permissions(context, self.admin_users, "delete_app")
        if self.store is None:
            raise RuntimeError("no artifact store configured")
        self.store.delete(artifact_id, version)
        return {"artifact_id": artifact_id, "deleted": True}

    # ---- app workdir management --------------------------------------------

    def list_app_directories(self, context: Optional[dict] = None) -> list[dict]:
        check_permissions(context, self.admin_users, "list_app_directories")
        root = self.builder.workdir_root
        if not root.exists():
            return []
        out = []
        for d in sorted(p for p in root.iterdir() if p.is_dir()):
            size = sum(f.stat().st_size for f in d.rglob("*") if f.is_file())
            out.append(
                {
                    "app_id": d.name,
                    "size_bytes": size,
                    "in_use": d.name in self.records,
                }
            )
        return out

    def clear_app_directory(
        self, app_id: str, context: Optional[dict] = None
    ) -> dict:
        check_permissions(context, self.admin_users, "clear_app_directory")
        if app_id in self.records:
            raise RuntimeError(f"app '{app_id}' is deployed; stop it first")
        target = self.builder.workdir_root / app_id
        if target.exists():
            shutil.rmtree(target)
            return {"app_id": app_id, "cleared": True}
        return {"app_id": app_id, "cleared": False}

    # ---- monitoring / recovery ----------------------------------------------

    async def monitor_applications(self) -> None:
        """One monitor pass: redeploy apps that went UNHEALTHY or
        DEPLOY_FAILED when auto_redeploy is set (ref manager.py:1003-1071);
        keep service registration in sync with health."""
        for app_id, record in list(self.records.items()):
            app = self.controller.apps.get(app_id)
            status = app.status if app else "DEPLOY_FAILED"
            if status == "RUNNING":
                if not record.proxy.registered:
                    record.proxy.register()
                continue
            if status == "UNHEALTHY" and record.proxy.registered:
                # drop the public service the moment the app is bad
                record.proxy.deregister()
            if (
                status in ("UNHEALTHY", "DEPLOY_FAILED")
                and record.auto_redeploy
                and record.redeploy_count < self.max_auto_redeploys
            ):
                record.redeploy_count += 1
                self.logger.warning(
                    f"auto-redeploying '{app_id}' "
                    f"(attempt {record.redeploy_count})"
                )
                admin_ctx = {
                    "user": {"id": self.admin_users[0] if self.admin_users else "system"},
                    "ws": "bioengine",
                }
                try:
                    await self.deploy_app(
                        artifact_id=record.artifact_id,
                        version=record.version,
                        local_path=record.local_path,
                        app_id=app_id,
                        deployment_kwargs=record.deployment_kwargs,
                        env_vars=record.env_vars,
                        authorized_users=record.authorized_users,
                        auto_redeploy=True,
                        context=admin_ctx,
                    )
                    self.records[app_id].redeploy_count = record.redeploy_count
                except Exception as e:
                    self.logger.error(f"auto-redeploy of '{app_id}' failed: {e}")

    async def deploy_startup_applications(
        self, startup_applications: list[dict]
    ) -> list[dict]:
        """Deploy the configured startup apps with admin context
        (ref manager.py:937-1001)."""
        admin_ctx = create_context(
            self.admin_users[0] if self.admin_users else "system",
            workspace="bioengine",
        )
        results = []
        for app_config in startup_applications:
            try:
                results.append(
                    await self.deploy_app(**app_config, context=admin_ctx)
                )
            except Exception as e:
                self.logger.error(
                    f"startup app {app_config} failed to deploy: {e}"
                )
                results.append({"error": str(e), "config": app_config})
        return results

    # ---- service surface ----------------------------------------------------

    def service_methods(self) -> dict[str, Any]:
        return {
            "deploy_app": self.deploy_app,
            "stop_app": self.stop_app,
            "stop_all_apps": self.stop_all_apps,
            "get_app_status": self.get_app_status,
            "list_apps": self.list_apps,
            "upload_app": self.upload_app,
            "get_app_manifest": self.get_app_manifest,
            "delete_app": self.delete_app,
            "list_app_directories": self.list_app_directories,
            "clear_app_directory": self.clear_app_directory,
        }
