"""Versioned application artifact store.

The reference stores app packages in a remote Hypha artifact manager
with staged versioning: saving a NEW version snapshots the current one;
re-saving the LATEST version updates in place; re-saving an OLDER
version is an error (ref bioengine/utils/artifact_utils.py:320-478).
This module provides the same semantics over a local directory tree —
which also serves as the test/dev override the reference exposes via
``BIOENGINE_LOCAL_ARTIFACT_PATH`` (ref apps/builder.py:268-279).

Layout: ``root/<artifact_id>/<version>/{manifest.yaml, *.py, ...}``
with a ``latest`` marker file naming the current version.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Optional

from bioengine_tpu.apps.manifest import AppManifest, load_manifest


class ArtifactVersionError(ValueError):
    pass


class LocalArtifactStore:
    def __init__(self, root: str | Path):
        self.root = Path(root).expanduser()
        self.root.mkdir(parents=True, exist_ok=True)

    # ---- read ---------------------------------------------------------------

    def list_artifacts(self) -> list[str]:
        return sorted(
            p.name
            for p in self.root.iterdir()
            if p.is_dir() and (p / "latest").exists()
        )

    def versions(self, artifact_id: str) -> list[str]:
        adir = self.root / artifact_id
        if not adir.exists():
            raise KeyError(f"artifact '{artifact_id}' not found")
        return sorted(
            p.name for p in adir.iterdir() if p.is_dir()
        )

    def latest_version(self, artifact_id: str) -> str:
        marker = self.root / artifact_id / "latest"
        if not marker.exists():
            raise KeyError(f"artifact '{artifact_id}' not found")
        return marker.read_text().strip()

    def artifact_dir(self, artifact_id: str, version: Optional[str] = None) -> Path:
        version = version or self.latest_version(artifact_id)
        d = self.root / artifact_id / version
        if not d.exists():
            raise KeyError(f"{artifact_id}@{version} not found")
        return d

    def get_manifest(
        self, artifact_id: str, version: Optional[str] = None
    ) -> AppManifest:
        return load_manifest(self.artifact_dir(artifact_id, version))

    def get_file(
        self, artifact_id: str, path: str, version: Optional[str] = None
    ) -> bytes:
        base = self.artifact_dir(artifact_id, version)
        f = (base / path).resolve()
        # defense in depth: paths can arrive from HTTP routes
        # (apps/artifact_http.py) — never read outside the version dir
        if not f.is_relative_to(base.resolve()):
            raise FileNotFoundError(f"{artifact_id}: path escapes artifact")
        if not f.is_file():
            raise FileNotFoundError(f"{artifact_id}@{version or 'latest'}:{path}")
        return f.read_bytes()

    def list_files(
        self, artifact_id: str, version: Optional[str] = None
    ) -> list[str]:
        d = self.artifact_dir(artifact_id, version)
        return sorted(
            str(p.relative_to(d)) for p in d.rglob("*") if p.is_file()
        )

    # ---- write (versioned staging semantics) --------------------------------

    def put(
        self,
        src_dir: str | Path,
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
    ) -> tuple[str, str]:
        """Upload an app directory. Returns (artifact_id, version).

        Version rules (parity with ref artifact_utils.py:320-478):
        - no existing artifact: creates it at ``version`` (default from
          manifest, then "1.0.0")
        - version == latest: in-place re-save
        - version > latest (new): snapshot as the new latest
        - version < latest: error
        """
        src = Path(src_dir)
        manifest = load_manifest(src)
        artifact_id = artifact_id or manifest.id
        version = version or manifest.version
        adir = self.root / artifact_id
        marker = adir / "latest"
        if marker.exists():
            latest = marker.read_text().strip()
            if version != latest:
                if _version_key(version) < _version_key(latest):
                    raise ArtifactVersionError(
                        f"cannot re-save older version {version} "
                        f"(latest is {latest})"
                    )
        dest = adir / version
        if dest.exists():
            shutil.rmtree(dest)
        dest.mkdir(parents=True)
        for p in src.rglob("*"):
            if p.is_file():
                rel = p.relative_to(src)
                target = dest / rel
                target.parent.mkdir(parents=True, exist_ok=True)
                shutil.copy2(p, target)
        adir.mkdir(exist_ok=True)
        marker.write_text(version)
        return artifact_id, version

    def put_files(
        self,
        files: dict[str, bytes | str],
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
    ) -> tuple[str, str]:
        """Upload from an in-memory {relative_path: content} mapping —
        the wire form used by remote CLI uploads, where the client's
        filesystem is not visible to the worker."""
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            base = Path(tmp)
            for rel, content in files.items():
                target = (base / rel).resolve()
                if not str(target).startswith(str(base.resolve())):
                    raise ValueError(f"path traversal in upload: '{rel}'")
                target.parent.mkdir(parents=True, exist_ok=True)
                if isinstance(content, str):
                    content = content.encode()
                target.write_bytes(content)
            return self.put(base, artifact_id, version)

    def delete(self, artifact_id: str, version: Optional[str] = None) -> None:
        adir = self.root / artifact_id
        if not adir.exists():
            raise KeyError(f"artifact '{artifact_id}' not found")
        if version is None:
            shutil.rmtree(adir)
            return
        target = adir / version
        if not target.exists():
            raise KeyError(f"{artifact_id}@{version} not found")
        shutil.rmtree(target)
        marker = adir / "latest"
        remaining = sorted(
            (p.name for p in adir.iterdir() if p.is_dir()), key=_version_key
        )
        if remaining:
            marker.write_text(remaining[-1])
        else:
            shutil.rmtree(adir)


def _version_key(v: str) -> tuple:
    parts = []
    for piece in str(v).replace("-", ".").split("."):
        parts.append((0, int(piece)) if piece.isdigit() else (1, piece))
    return tuple(parts)
