"""MCP (Model Context Protocol) endpoint per deployed app.

The reference registers an MCP-type Hypha service alongside each app's
WebSocket service so agent frameworks can call the app's schema methods
as tools (ref bioengine/apps/proxy_deployment.py:834). This framework
serves the protocol itself: every deployed app gets a streamable-HTTP
MCP endpoint at ``POST /mcp/{app_id}`` on the RPC server, speaking
JSON-RPC 2.0:

- ``initialize``                capability/serverInfo handshake
- ``notifications/initialized`` accepted (202, no body)
- ``ping``                      liveness
- ``tools/list``                one tool per entry ``@schema_method``
                                (inputSchema = the method's parameter
                                schema, rpc/schema.py)
- ``tools/call``                routes through the app proxy, so the
                                SAME per-method ACL applies as on the
                                websocket plane (apps/proxy.py)

Auth mirrors the JSON HTTP bridge: Bearer/query token -> caller
context; anonymous otherwise (public apps with ``*`` ACLs work
unauthenticated, locked apps reject).
"""

from __future__ import annotations

import json
from typing import Any, Optional

PROTOCOL_VERSION = "2024-11-05"
SERVER_VERSION = "0.1.0"

# JSON-RPC error codes
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603


def tool_list(schema_methods: dict[str, dict]) -> list[dict]:
    """MCP tool descriptors from an app's schema methods."""
    tools = []
    for name, schema in sorted(schema_methods.items()):
        tools.append(
            {
                "name": name,
                "description": schema.get("description", ""),
                "inputSchema": schema.get(
                    "parameters", {"type": "object", "properties": {}}
                ),
            }
        )
    return tools


async def handle_message(
    proxy, body: dict, context: Optional[dict]
) -> Optional[dict]:
    """One JSON-RPC message against an app's proxy. Returns the response
    object, or None for notifications (HTTP 202)."""
    msg_id = body.get("id")
    method = body.get("method", "")
    params = body.get("params") or {}

    def result(payload: Any) -> dict:
        return {"jsonrpc": "2.0", "id": msg_id, "result": payload}

    def error(code: int, message: str) -> dict:
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "error": {"code": code, "message": message},
        }

    if method.startswith("notifications/"):
        return None
    if method == "initialize":
        # echo a client-requested version (our JSON-RPC subset is wire-
        # identical across revisions); fall back to our baseline
        requested = params.get("protocolVersion")
        return result(
            {
                "protocolVersion": requested or PROTOCOL_VERSION,
                "capabilities": {"tools": {"listChanged": False}},
                "serverInfo": {
                    "name": f"bioengine-{proxy.built.app_id}",
                    "version": SERVER_VERSION,
                },
                "instructions": proxy.built.manifest.description,
            }
        )
    if method == "ping":
        return result({})
    if method == "tools/list":
        return result({"tools": tool_list(proxy.built.schema_methods)})
    if method == "tools/call":
        name = params.get("name", "")
        if name not in proxy.built.schema_methods:
            return error(INVALID_PARAMS, f"unknown tool '{name}'")
        arguments = params.get("arguments") or {}
        if not isinstance(arguments, dict):
            return error(INVALID_PARAMS, "arguments must be an object")
        # 'context' is reserved for server-injected caller identity on
        # every plane — never accept a caller-supplied one
        arguments.pop("context", None)
        try:
            value = await proxy.call_method(name, arguments, context)
        except PermissionError as e:
            return result(
                {
                    "content": [{"type": "text", "text": f"Permission denied: {e}"}],
                    "isError": True,
                }
            )
        except Exception as e:
            return result(
                {
                    "content": [
                        {"type": "text", "text": f"{type(e).__name__}: {e}"}
                    ],
                    "isError": True,
                }
            )
        return result(
            {
                "content": [
                    {"type": "text", "text": json.dumps(_jsonable(value))}
                ],
                "isError": False,
            }
        )
    return error(METHOD_NOT_FOUND, f"method '{method}' not supported")


def _jsonable(obj: Any) -> Any:
    # shares the bridge's conversion incl. non-finite-float -> null
    # (MCP clients parse with strict JSON too)
    from bioengine_tpu.rpc.server import _to_jsonable

    return _to_jsonable(obj)
