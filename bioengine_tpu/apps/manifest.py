"""App manifest schema + validation.

Same manifest contract as the reference so existing app directories port
unchanged (ref bioengine/apps/builder.py:29-67: required name/id/
id_emoji/description/type/deployments, optional frontend_entry;
``deployments`` entries are "file_stem:ClassName"). The TPU build adds
optional per-deployment resource hints (``deployment_config``) including
a mesh spec.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Any, Optional

import yaml

REQUIRED_FIELDS = ("name", "id", "id_emoji", "description", "type", "deployments")
# accept the reference's type string so existing manifests work verbatim
ACCEPTED_TYPES = ("tpu-serve", "ray-serve")

_DEPLOYMENT_RE = re.compile(r"^([A-Za-z_][\w\-/]*):([A-Za-z_]\w*)$")


class ManifestError(ValueError):
    pass


@dataclasses.dataclass
class DeploymentRef:
    file_stem: str
    class_name: str

    @property
    def python_file(self) -> str:
        return f"{self.file_stem}.py"


@dataclasses.dataclass
class AppManifest:
    name: str
    id: str
    id_emoji: str
    description: str
    type: str
    deployments: list[DeploymentRef]
    version: str = "1.0.0"
    frontend_entry: Optional[str] = None
    authorized_users: list[str] = dataclasses.field(default_factory=list)
    deployment_config: dict[str, dict] = dataclasses.field(default_factory=dict)
    raw: dict = dataclasses.field(default_factory=dict)

    @property
    def entry_deployment(self) -> DeploymentRef:
        """First listed deployment is the entry point (the service
        surface), matching the reference's convention."""
        return self.deployments[0]


def validate_manifest(data: dict[str, Any]) -> AppManifest:
    missing = [f for f in REQUIRED_FIELDS if not data.get(f)]
    if missing:
        raise ManifestError(f"manifest missing required fields: {missing}")
    if data["type"] not in ACCEPTED_TYPES:
        raise ManifestError(
            f"manifest type must be one of {ACCEPTED_TYPES}, "
            f"got '{data['type']}'"
        )
    deployments = []
    for entry in data["deployments"]:
        m = _DEPLOYMENT_RE.match(str(entry))
        if not m:
            raise ManifestError(
                f"deployment entry '{entry}' is not 'file_stem:ClassName'"
            )
        deployments.append(DeploymentRef(m.group(1), m.group(2)))
    if not deployments:
        raise ManifestError("manifest needs at least one deployment")
    return AppManifest(
        name=str(data["name"]),
        id=str(data["id"]),
        id_emoji=str(data["id_emoji"]),
        description=str(data["description"]),
        type=data["type"],
        deployments=deployments,
        version=str(data.get("version", "1.0.0")),
        frontend_entry=data.get("frontend_entry"),
        authorized_users=list(data.get("authorized_users", []) or []),
        deployment_config={
            k: dict(v) for k, v in (data.get("deployment_config") or {}).items()
        },
        raw=dict(data),
    )


def load_manifest(path: str | Path) -> AppManifest:
    path = Path(path)
    if path.is_dir():
        path = path / "manifest.yaml"
    if not path.exists():
        raise ManifestError(f"no manifest at {path}")
    return validate_manifest(yaml.safe_load(path.read_text()) or {})
