"""App manifest schema + validation.

Same manifest contract as the reference so existing app directories port
unchanged (ref bioengine/apps/builder.py:29-67: required name/id/
id_emoji/description/type/deployments, optional frontend_entry;
``deployments`` entries are "file_stem:ClassName"). The TPU build adds
optional per-deployment resource hints (``deployment_config``) including
a mesh spec.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Any, Optional

import yaml

REQUIRED_FIELDS = ("name", "id", "id_emoji", "description", "type", "deployments")
# accept the reference's type string so existing manifests work verbatim
ACCEPTED_TYPES = ("tpu-serve", "ray-serve")

_DEPLOYMENT_RE = re.compile(r"^([A-Za-z_][\w\-/]*):([A-Za-z_]\w*)$")

# operator-tunable per-deployment blocks with a fixed vocabulary —
# validated here so a typo fails the manifest, not a live deploy.
# ``batching`` feeds the replica's ContinuousBatcher (injected as
# bioengine_batch_config); ``scheduling`` opts the deployment into the
# controller's global scheduler (key set validated in depth by
# serving.scheduler.SchedulingConfig.from_config at build time);
# ``slo`` declares the deployment's service objectives (validated in
# depth by serving.slo.SLOConfig.from_config at build time — latency
# objective + percentile, availability target, window); ``warm_pool``
# keeps N pre-started standby replicas that absorb scale-up and
# preemption by promotion (validated in depth by
# serving.warm_pool.WarmPoolConfig.from_config at build time);
# ``mesh`` places one logical replica across several hosts' chip
# leases — pipeline/dp/tp shards for checkpoints bigger than one lease
# (validated in depth by serving.mesh_plan.MeshConfig.from_config).
_BATCHING_KEYS = {"max_batch", "max_wait_ms"}


class ManifestError(ValueError):
    pass


@dataclasses.dataclass
class DeploymentRef:
    file_stem: str
    class_name: str

    @property
    def python_file(self) -> str:
        return f"{self.file_stem}.py"


@dataclasses.dataclass
class AppManifest:
    name: str
    id: str
    id_emoji: str
    description: str
    type: str
    deployments: list[DeploymentRef]
    version: str = "1.0.0"
    frontend_entry: Optional[str] = None
    authorized_users: list[str] = dataclasses.field(default_factory=list)
    deployment_config: dict[str, dict] = dataclasses.field(default_factory=dict)
    raw: dict = dataclasses.field(default_factory=dict)

    @property
    def entry_deployment(self) -> DeploymentRef:
        """First listed deployment is the entry point (the service
        surface), matching the reference's convention."""
        return self.deployments[0]


def validate_manifest(data: dict[str, Any]) -> AppManifest:
    missing = [f for f in REQUIRED_FIELDS if not data.get(f)]
    if missing:
        raise ManifestError(f"manifest missing required fields: {missing}")
    if data["type"] not in ACCEPTED_TYPES:
        raise ManifestError(
            f"manifest type must be one of {ACCEPTED_TYPES}, "
            f"got '{data['type']}'"
        )
    deployments = []
    for entry in data["deployments"]:
        m = _DEPLOYMENT_RE.match(str(entry))
        if not m:
            raise ManifestError(
                f"deployment entry '{entry}' is not 'file_stem:ClassName'"
            )
        deployments.append(DeploymentRef(m.group(1), m.group(2)))
    if not deployments:
        raise ManifestError("manifest needs at least one deployment")
    for dep_name, cfg in (data.get("deployment_config") or {}).items():
        if not isinstance(cfg, dict):
            raise ManifestError(
                f"deployment_config.{dep_name} must be a mapping, got "
                f"{type(cfg).__name__}"
            )
        batching = cfg.get("batching")
        if batching is not None:
            if not isinstance(batching, dict):
                raise ManifestError(
                    f"deployment_config.{dep_name}.batching must be a "
                    f"mapping, got {type(batching).__name__}"
                )
            unknown = sorted(set(batching) - _BATCHING_KEYS)
            if unknown:
                raise ManifestError(
                    f"deployment_config.{dep_name}.batching has unknown "
                    f"keys {unknown} (accepted: {sorted(_BATCHING_KEYS)})"
                )
        scheduling = cfg.get("scheduling")
        if scheduling is not None and not isinstance(scheduling, dict):
            raise ManifestError(
                f"deployment_config.{dep_name}.scheduling must be a "
                f"mapping, got {type(scheduling).__name__}"
            )
        slo = cfg.get("slo")
        if slo is not None and not isinstance(slo, dict):
            raise ManifestError(
                f"deployment_config.{dep_name}.slo must be a "
                f"mapping, got {type(slo).__name__}"
            )
        warm_pool = cfg.get("warm_pool")
        if warm_pool is not None and not isinstance(warm_pool, dict):
            raise ManifestError(
                f"deployment_config.{dep_name}.warm_pool must be a "
                f"mapping, got {type(warm_pool).__name__}"
            )
        mesh = cfg.get("mesh")
        if mesh is not None and not isinstance(mesh, dict):
            raise ManifestError(
                f"deployment_config.{dep_name}.mesh must be a "
                f"mapping, got {type(mesh).__name__}"
            )
    return AppManifest(
        name=str(data["name"]),
        id=str(data["id"]),
        id_emoji=str(data["id_emoji"]),
        description=str(data["description"]),
        type=data["type"],
        deployments=deployments,
        version=str(data.get("version", "1.0.0")),
        frontend_entry=data.get("frontend_entry"),
        authorized_users=list(data.get("authorized_users", []) or []),
        deployment_config={
            k: dict(v) for k, v in (data.get("deployment_config") or {}).items()
        },
        raw=dict(data),
    )


def load_manifest(path: str | Path) -> AppManifest:
    path = Path(path)
    if path.is_dir():
        path = path / "manifest.yaml"
    if not path.exists():
        raise ManifestError(f"no manifest at {path}")
    return validate_manifest(yaml.safe_load(path.read_text()) or {})
