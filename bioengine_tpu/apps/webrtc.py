"""WebRTC data-channel transport per app — gated on aiortc.

The reference registers a WebRTC service per app next to the WebSocket
one: clients fetch ICE servers, open a peer connection, and call app
methods over data channels; the deployment tracks open PCs for load
reporting (ref bioengine/apps/proxy_deployment.py:599-732, 950-992).

aiortc (C-backed) is an OPTIONAL dependency of this framework — TPU
worker images ship without it (SURVEY.md environment: stub or gate
anything not baked in). This module is the gate: when aiortc is
importable the proxy registers an ``{app_id}-rtc`` signaling service
whose ``offer`` verb answers SDP offers and serves ACL-checked app
calls over a ``rpc`` data channel (JSON ``{id, method, kwargs}`` ->
``{id, result | error}``); without aiortc, registration is skipped
with a log line and everything else works over WebSocket/HTTP/MCP.
"""

from __future__ import annotations

import json
from typing import Any, Optional


def webrtc_available() -> bool:
    try:
        import aiortc  # noqa: F401

        return True
    except ImportError:
        return False


def maybe_register_rtc(server, proxy) -> Optional[str]:
    """Register the app's WebRTC signaling service when aiortc exists.
    Returns the service id, or None when gated off."""
    if not webrtc_available():
        proxy.logger.info(
            "aiortc not installed — WebRTC transport gated off for "
            f"'{proxy.built.app_id}' (WebSocket/HTTP/MCP remain)"
        )
        return None
    return _register(server, proxy)


def close_rtc_pcs(proxy) -> int:
    """Close every peer connection an app's RTC service still holds
    (called from proxy.deregister — undeploy must not leak ICE/DTLS
    sockets). Returns how many closes were scheduled."""
    import asyncio

    pcs = getattr(proxy, "_rtc_pcs", None)
    if not pcs:
        return 0
    n = len(pcs)
    for pc in list(pcs):
        asyncio.ensure_future(pc.close())
    pcs.clear()
    return n


def _register(server, proxy) -> str:
    from aiortc import RTCPeerConnection, RTCSessionDescription

    pcs: set[Any] = set()
    proxy._rtc_pcs = pcs  # close_rtc_pcs reaches them on deregister

    async def offer(sdp: str, type: str = "offer", context=None) -> dict:
        """Answer an SDP offer; app methods ride the 'rpc' data channel
        with the caller context captured at signaling time (the ACL
        decision uses the SAME identity as the websocket plane).
        NB the wire field is named ``type`` (SDP convention)."""
        sdp_type = type
        pc = RTCPeerConnection()
        pcs.add(pc)

        @pc.on("connectionstatechange")
        async def _on_state():
            if pc.connectionState in ("failed", "closed"):
                pcs.discard(pc)

        @pc.on("datachannel")
        def _on_channel(channel):
            @channel.on("message")
            def _on_message(message):
                import asyncio

                async def respond():
                    req = None
                    try:
                        req = json.loads(message)
                        value = await proxy.call_method(
                            req["method"], req.get("kwargs") or {}, context
                        )
                        channel.send(
                            json.dumps({"id": req.get("id"), "result": value})
                        )
                    except Exception as e:
                        channel.send(
                            json.dumps(
                                {
                                    "id": (req.get("id")
                                           if isinstance(req, dict) else None),
                                    "error": f"{e.__class__.__name__}: {e}",
                                }
                            )
                        )

                asyncio.ensure_future(respond())

        await pc.setRemoteDescription(
            RTCSessionDescription(sdp=sdp, type=sdp_type)
        )
        answer = await pc.createAnswer()
        await pc.setLocalDescription(answer)
        return {
            "sdp": pc.localDescription.sdp,
            "type": pc.localDescription.type,
        }

    def get_num_pcs(context=None) -> int:
        return len(pcs)

    entry = server.register_local_service(
        {
            "id": f"{proxy.built.app_id}-rtc",
            "name": f"{proxy.built.manifest.name} (WebRTC)",
            "type": "bioengine-app-rtc",
            "config": {"require_context": True, "visibility": "public"},
            "offer": offer,
            "get_num_pcs": get_num_pcs,
        }
    )
    proxy.logger.info(f"registered WebRTC service {entry.full_id}")
    return entry.full_id
