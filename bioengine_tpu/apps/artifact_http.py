"""HTTP artifact service + remote client.

The reference keeps app packages in Hypha's remote artifact manager:
clients request a presigned PUT URL per file, upload over plain HTTP,
then ``commit`` finalizes the staged version; artifacts get a public
static-site URL (ref bioengine/utils/artifact_utils.py:481-548,
600-628). This framework ships its own control plane, so the artifact
manager is part of it: ``ArtifactHttpService`` mounts the same
capability surface on the RPC server's HTTP app, backed by a
``LocalArtifactStore``; ``RemoteArtifactStore`` is the client side,
interface-compatible with ``LocalArtifactStore`` so AppBuilder /
AppsManager work against either transparently.

Routes (mounted under ``/artifacts``):

- ``GET    /artifacts``                          list artifact ids
- ``GET    /artifacts/{id}``                     {versions, latest}
- ``GET    /artifacts/{id}/manifest?version=``   manifest (yaml text)
- ``GET    /artifacts/{id}/files?version=``      file listing
- ``GET    /artifacts/{id}/files/{path}?version=``  file bytes
- ``GET    /artifacts/{id}/view/{path}``         static site (latest)
- ``POST   /artifacts/{id}/put_url``   admin: presign one file upload
- ``PUT    /artifacts/{id}/upload/{path}?sig=``  upload to the stage
- ``POST   /artifacts/{id}/commit``    admin: finalize staged version
- ``DELETE /artifacts/{id}?version=``  admin: delete
"""

from __future__ import annotations

import mimetypes
import secrets
import time
from typing import TYPE_CHECKING, Optional

from aiohttp import web

from bioengine_tpu.apps.artifacts import ArtifactVersionError, LocalArtifactStore
from bioengine_tpu.utils.logger import create_logger

if TYPE_CHECKING:  # pragma: no cover
    from bioengine_tpu.rpc.server import RpcServer

UPLOAD_GRANT_TTL = 600.0
STAGE_TTL = 3600.0                      # abandoned uploads are dropped
STAGE_MAX_BYTES = 1 << 30               # total in-RAM staging budget


def _check_rel_path(path: str) -> str:
    """Reject traversal in a client-supplied artifact-relative path —
    aiohttp delivers dot segments verbatim when the client sends them
    raw, so every read AND write route must check."""
    if not path or path.startswith("/") or ".." in path.split("/"):
        raise ValueError(f"bad artifact path '{path}'")
    return path


class ArtifactHttpService:
    def __init__(
        self,
        store: LocalArtifactStore,
        rpc_server: "RpcServer",
        log_file: Optional[str] = None,
    ):
        self.store = store
        self.rpc = rpc_server
        self.logger = create_logger("artifacts.http", log_file=log_file)
        # sig -> (artifact_id, path, expires_at)
        self._grants: dict[str, tuple[str, str, float]] = {}
        # artifact_id -> {path: bytes} staged since the last commit
        self._staged: dict[str, dict[str, bytes]] = {}
        self._stage_touched: dict[str, float] = {}

    def _gc(self) -> None:
        """Drop expired grants and abandoned stages — a client that
        presigns or uploads and never commits must not pin worker RAM
        forever."""
        now = time.time()
        for sig in [s for s, g in self._grants.items() if now > g[2]]:
            del self._grants[sig]
        for aid in [
            a
            for a, t in self._stage_touched.items()
            if now - t > STAGE_TTL
        ]:
            self._staged.pop(aid, None)
            del self._stage_touched[aid]

    def _staged_bytes(self) -> int:
        return sum(
            len(b) for files in self._staged.values() for b in files.values()
        )

    # ---- auth ---------------------------------------------------------------

    def _require_admin(self, request: web.Request) -> None:
        auth = request.headers.get("Authorization", "")
        token = auth[len("Bearer "):] if auth.startswith("Bearer ") else (
            request.query.get("token", "")
        )
        info = self.rpc.validate_token(token)  # raises PermissionError
        if not info.is_admin:
            raise PermissionError("artifact writes require an admin token")

    # ---- dispatch -----------------------------------------------------------

    async def handle(self, request: web.Request) -> web.Response:
        """Route ``/artifacts...`` requests (mounted as a catch-all on
        the RPC server's HTTP app)."""
        parts = [p for p in request.path.split("/") if p][1:]  # drop 'artifacts'
        try:
            return await self._route(request, parts)
        except PermissionError as e:
            return web.json_response({"error": str(e)}, status=401)
        except ArtifactVersionError as e:
            return web.json_response({"error": str(e)}, status=409)
        except (KeyError, FileNotFoundError) as e:
            return web.json_response({"error": str(e)}, status=404)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)

    async def _route(
        self, request: web.Request, parts: list[str]
    ) -> web.Response:
        method = request.method
        if not parts:
            return web.json_response(self.store.list_artifacts())
        aid = parts[0]
        rest = parts[1:]
        version = request.query.get("version") or None

        if method == "GET":
            if not rest:
                return web.json_response(
                    {
                        "artifact_id": aid,
                        "versions": self.store.versions(aid),
                        "latest": self.store.latest_version(aid),
                    }
                )
            if rest == ["manifest"]:
                data = self.store.get_file(aid, "manifest.yaml", version)
                return web.Response(body=data, content_type="text/yaml")
            if rest == ["files"]:
                return web.json_response(self.store.list_files(aid, version))
            if rest[0] == "files":
                path = _check_rel_path("/".join(rest[1:]))
                return self._file_response(aid, path, version)
            if rest[0] == "view":
                path = _check_rel_path("/".join(rest[1:]) or "index.html")
                return self._file_response(aid, path, None, inline=True)
        elif method == "POST" and rest == ["put_url"]:
            self._require_admin(request)
            self._gc()
            body = await request.json()
            path = _check_rel_path(body.get("path", ""))
            sig = secrets.token_urlsafe(24)
            self._grants[sig] = (aid, path, time.time() + UPLOAD_GRANT_TTL)
            return web.json_response(
                {"url": f"/artifacts/{aid}/upload/{path}?sig={sig}"}
            )
        elif method == "PUT" and rest and rest[0] == "upload":
            path = "/".join(rest[1:])
            sig = request.query.get("sig", "")
            grant = self._grants.get(sig)
            if (
                grant is None
                or grant[0] != aid
                or grant[1] != path
                or time.time() > grant[2]
            ):
                raise PermissionError("invalid or expired upload signature")
            del self._grants[sig]
            data = await request.read()
            if self._staged_bytes() + len(data) > STAGE_MAX_BYTES:
                raise ValueError(
                    "staging area full — commit or abandon pending uploads"
                )
            self._staged.setdefault(aid, {})[path] = data
            self._stage_touched[aid] = time.time()
            return web.json_response({"staged": path})
        elif method == "POST" and rest == ["commit"]:
            self._require_admin(request)
            body = await request.json() if request.can_read_body else {}
            staged = self._staged.pop(aid, None)
            self._stage_touched.pop(aid, None)
            if not staged:
                raise ValueError(f"nothing staged for '{aid}'")
            try:
                artifact_id, committed = self.store.put_files(
                    staged, artifact_id=aid, version=body.get("version")
                )
            except Exception:
                # commit failed: keep the stage for a retry
                self._staged[aid] = staged
                self._stage_touched[aid] = time.time()
                raise
            self.logger.info(
                f"committed {artifact_id}@{committed} ({len(staged)} files)"
            )
            return web.json_response(
                {"artifact_id": artifact_id, "version": committed}
            )
        elif method == "DELETE" and not rest:
            self._require_admin(request)
            self.store.delete(aid, version)
            return web.json_response({"deleted": aid, "version": version})
        raise KeyError(f"no artifact route {method} {request.path}")

    def _file_response(
        self,
        aid: str,
        path: str,
        version: Optional[str],
        inline: bool = False,
    ) -> web.Response:
        data = self.store.get_file(aid, path, version)
        ctype = None
        if inline:
            ctype = mimetypes.guess_type(path)[0] or "application/octet-stream"
        return web.Response(
            body=data, content_type=ctype or "application/octet-stream"
        )

    @staticmethod
    def view_url(base_url: str, artifact_id: str) -> str:
        """Public static-site URL for an artifact (the analog of ref
        utils/artifact_utils.py:612-628)."""
        return f"{base_url}/artifacts/{artifact_id}/view/"


class RemoteArtifactStore:
    """Client for an ArtifactHttpService — same interface as
    LocalArtifactStore, so AppBuilder/AppsManager can stage and deploy
    from a remote controller's artifact manager."""

    def __init__(self, base_url: str, token: Optional[str] = None):
        import httpx

        self.base_url = base_url.rstrip("/")
        self.token = token
        self._http = httpx.Client(base_url=self.base_url, timeout=30.0)

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def _get(self, path: str, **params):
        r = self._http.get(path, params={k: v for k, v in params.items() if v})
        if r.status_code == 404:
            raise KeyError(r.json().get("error", path))
        r.raise_for_status()
        return r

    # ---- read (LocalArtifactStore interface) --------------------------------

    def list_artifacts(self) -> list[str]:
        return self._get("/artifacts").json()

    def versions(self, artifact_id: str) -> list[str]:
        return self._get(f"/artifacts/{artifact_id}").json()["versions"]

    def latest_version(self, artifact_id: str) -> str:
        return self._get(f"/artifacts/{artifact_id}").json()["latest"]

    def get_manifest(self, artifact_id: str, version: Optional[str] = None):
        import yaml

        from bioengine_tpu.apps.manifest import validate_manifest

        text = self._get(
            f"/artifacts/{artifact_id}/manifest", version=version
        ).text
        return validate_manifest(yaml.safe_load(text))

    def get_file(
        self, artifact_id: str, path: str, version: Optional[str] = None
    ) -> bytes:
        return self._get(
            f"/artifacts/{artifact_id}/files/{path}", version=version
        ).content

    def list_files(
        self, artifact_id: str, version: Optional[str] = None
    ) -> list[str]:
        return self._get(
            f"/artifacts/{artifact_id}/files", version=version
        ).json()

    # ---- write: presigned-PUT flow ------------------------------------------

    def put_files(
        self,
        files: dict[str, bytes | str],
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
    ) -> tuple[str, str]:
        """Presign + upload each file, then commit (the reference's
        put_file -> httpx PUT -> commit flow, ref
        utils/artifact_utils.py:481-548, 600-608)."""
        import yaml

        if artifact_id is None:
            manifest_src = files.get("manifest.yaml")
            if manifest_src is None:
                raise ValueError("upload needs manifest.yaml or artifact_id")
            if isinstance(manifest_src, bytes):
                manifest_src = manifest_src.decode()
            artifact_id = yaml.safe_load(manifest_src)["id"]
        for rel, content in files.items():
            r = self._http.post(
                f"/artifacts/{artifact_id}/put_url",
                json={"path": rel},
                headers=self._headers(),
            )
            r.raise_for_status()
            url = r.json()["url"]
            if isinstance(content, str):
                content = content.encode()
            up = self._http.put(url, content=content)
            up.raise_for_status()
        r = self._http.post(
            f"/artifacts/{artifact_id}/commit",
            json={"version": version},
            headers=self._headers(),
        )
        if r.status_code == 409:
            raise ArtifactVersionError(r.json().get("error", "version conflict"))
        r.raise_for_status()
        data = r.json()
        return data["artifact_id"], data["version"]

    def put(
        self,
        src_dir,
        artifact_id: Optional[str] = None,
        version: Optional[str] = None,
    ) -> tuple[str, str]:
        from pathlib import Path

        src = Path(src_dir)
        files = {
            str(p.relative_to(src)): p.read_bytes()
            for p in src.rglob("*")
            if p.is_file()
        }
        return self.put_files(files, artifact_id, version)

    def delete(self, artifact_id: str, version: Optional[str] = None) -> None:
        r = self._http.delete(
            f"/artifacts/{artifact_id}",
            params={"version": version} if version else {},
            headers=self._headers(),
        )
        if r.status_code == 404:
            raise KeyError(artifact_id)
        r.raise_for_status()

    def view_url(self, artifact_id: str) -> str:
        return ArtifactHttpService.view_url(self.base_url, artifact_id)

    def close(self) -> None:
        self._http.close()
