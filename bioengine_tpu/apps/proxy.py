"""App service proxy — publish an app's entry methods on the RPC plane
with per-method access control.

The reference's ProxyDeployment registers one schema_function per entry
``@schema_method`` on Hypha, enforces per-method ACLs (method-specific >
wildcard > deny, ref bioengine/apps/proxy_deployment.py:345-403), counts
in-flight requests, and deregisters the service the moment the entry
goes unhealthy (:997-1088). Same responsibilities here, minus the
mimic-request autoscaling hack — the controller measures load natively.
"""

from __future__ import annotations

from typing import Any, Optional

from bioengine_tpu.apps.builder import BuiltApp
from bioengine_tpu.rpc.server import RpcServer
from bioengine_tpu.serving.controller import DeploymentHandle, ServeController
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.permissions import check_method_permission


class AppServiceProxy:
    def __init__(
        self,
        server: RpcServer,
        controller: ServeController,
        built: BuiltApp,
        log_file: Optional[str] = None,
    ):
        self.server = server
        self.controller = controller
        self.built = built
        self.service_id: Optional[str] = None
        self.mcp_url: Optional[str] = None
        self.rtc_service_id: Optional[str] = None
        self.logger = create_logger(f"proxy.{built.app_id}", log_file=log_file)

    @property
    def handle(self) -> DeploymentHandle:
        return self.controller.get_handle(
            self.built.app_id, self.built.entry_name
        )

    def register(self) -> str:
        """Register one proxy function per entry schema method, plus the
        app's MCP endpoint (ref proxy_deployment.py:834 registers an
        MCP-type Hypha service; here the framework serves the protocol
        itself at /mcp/{app_id} — apps/mcp.py)."""
        built = self.built
        mcp_url = None
        register_mcp = getattr(self.server, "register_mcp_app", None)
        if register_mcp is not None:
            mcp_url = register_mcp(built.app_id, self)
        self.mcp_url = mcp_url
        # WebRTC transport: registers only when aiortc is installed
        # (apps/webrtc.py gate; ref proxy_deployment.py:599-732)
        from bioengine_tpu.apps.webrtc import maybe_register_rtc

        self.rtc_service_id = maybe_register_rtc(self.server, self)
        definition: dict[str, Any] = {
            "id": built.app_id,
            "name": built.manifest.name,
            "type": "bioengine-app",
            "description": built.manifest.description,
            "config": {
                "require_context": True,
                "visibility": "public",
                "mcp_url": mcp_url,
            },
        }
        for method_name, schema in built.schema_methods.items():
            definition[method_name] = self._make_proxy_fn(method_name, schema)
        definition["get_load"] = (
            lambda context=None: self.controller.get_load(built.app_id)
        )
        entry = self.server.register_local_service(definition)
        self.service_id = entry.full_id
        self.logger.info(f"registered service {self.service_id}")
        return self.service_id

    async def call_method(
        self, method_name: str, kwargs: dict, context: Optional[dict]
    ) -> Any:
        """ACL-checked call — the single enforcement point shared by the
        websocket proxy functions and the MCP tools/call path."""
        check_method_permission(
            self.built.authorized_users, method_name, context
        )
        return await self.handle.call(method_name, **kwargs)

    def _make_proxy_fn(self, method_name: str, schema: dict):
        async def proxy_fn(*args, context=None, **kwargs):
            if not args:
                return await self.call_method(method_name, kwargs, context)
            # positional calls can't ride the kwargs-only shared path
            check_method_permission(
                self.built.authorized_users, method_name, context
            )
            return await self.handle.call(method_name, *args, **kwargs)

        proxy_fn.__name__ = method_name
        proxy_fn.__doc__ = schema.get("description", "")
        proxy_fn.__schema__ = schema
        proxy_fn.__is_schema_method__ = True
        return proxy_fn

    def deregister(self) -> None:
        if self.service_id:
            unregister_mcp = getattr(self.server, "unregister_mcp_app", None)
            if unregister_mcp is not None:
                unregister_mcp(self.built.app_id)
            self.mcp_url = None
            if self.rtc_service_id:
                from bioengine_tpu.apps.webrtc import close_rtc_pcs

                close_rtc_pcs(self)
                self.server.unregister_service(self.rtc_service_id)
                self.rtc_service_id = None
            self.server.unregister_service(self.service_id)
            self.logger.info(f"deregistered service {self.service_id}")
            self.service_id = None

    @property
    def registered(self) -> bool:
        return self.service_id is not None
