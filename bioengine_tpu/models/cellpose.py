"""Cellpose-style flow-field segmentation model — the flagship model.

Replaces the reference's torch Cellpose-SAM fine-tuning path
(ref apps/cellpose-finetuning/main.py:1278-1360, single-GPU only) with a
JAX/Flax network + optax train step designed to run under pjit:

- The network predicts a 3-channel map per pixel: (flow_y, flow_x,
  cell_probability) — cellpose semantics.
- ``make_train_step`` returns a pure jittable step; wrap it in pjit with
  a dp-sharded batch and gradients are all-reduced over ICI for free
  (a capability the reference does not have at all — see SURVEY.md §2.3).
- Style vector: global average-pooled bottleneck features modulate the
  decoder, as in cellpose.

Mask reconstruction (flow following) lives in
``bioengine_tpu.ops.flows`` so inference postprocessing can run either
on host (numpy) or on device.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct


class ResBlock(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Pre-activation norm sees the *input* channel count, which can be
        # tiny (raw image channels) — group count must divide it.
        h = nn.GroupNorm(num_groups=math.gcd(32, x.shape[-1]), dtype=self.dtype)(x)
        h = nn.silu(h)
        h = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(h)
        h = nn.silu(h)
        h = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(h)
        if x.shape[-1] != self.features:
            x = nn.Conv(self.features, (1, 1), dtype=self.dtype)(x)
        return x + h


class StyleMod(nn.Module):
    """Inject the global style vector as a per-channel bias (cellpose-style)."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, style):
        bias = nn.Dense(self.features, dtype=self.dtype)(style)
        return x + bias[:, None, None, :]


class CellposeNet(nn.Module):
    """Residual U-Net with a global style vector.

    in: (B, H, W, C) images, H/W divisible by 2**(len(features)-1).
    out: (B, H, W, 3) — flow_y, flow_x, cellprob logits (f32).
    """

    features: Sequence[int] = (32, 64, 128, 256)
    in_channels: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        for feats in self.features[:-1]:
            x = ResBlock(feats, self.dtype)(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ResBlock(self.features[-1], self.dtype)(x)
        # Style: global average pool of bottleneck, L2-normalized.
        style = jnp.mean(x, axis=(1, 2))
        style = style / (jnp.linalg.norm(style.astype(jnp.float32), axis=-1, keepdims=True) + 1e-6).astype(self.dtype)
        for feats, skip in zip(reversed(self.features[:-1]), reversed(skips)):
            x = nn.ConvTranspose(feats, (2, 2), strides=(2, 2), dtype=self.dtype)(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ResBlock(feats, self.dtype)(x)
            x = StyleMod(feats, self.dtype)(x, style)
        x = nn.Conv(3, (1, 1), dtype=jnp.float32)(x)
        return x.astype(jnp.float32)

    @property
    def divisor(self) -> int:
        return 2 ** (len(self.features) - 1)


class TrainState(struct.PyTreeNode):
    """Minimal train state (params + opt state), pjit-shardable."""

    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, apply_fn, params, tx):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads):
        updates, opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=opt_state,
        )


def cellpose_loss(pred: jax.Array, flows: jax.Array, cellprob: jax.Array):
    """Cellpose objective: MSE on 5x-scaled flows + BCE on cell probability.

    pred: (B, H, W, 3); flows: (B, H, W, 2) target flow field in [-1, 1];
    cellprob: (B, H, W) binary target.
    """
    from bioengine_tpu.ops.flows import FLOW_SCALE

    flow_loss = 0.5 * jnp.mean((pred[..., :2] - FLOW_SCALE * flows) ** 2)
    bce = optax.sigmoid_binary_cross_entropy(pred[..., 2], cellprob)
    return flow_loss + jnp.mean(bce), {
        "flow_loss": flow_loss,
        "bce_loss": jnp.mean(bce),
    }


def make_loss_train_step(loss_call, dp_axis: str | None = None):
    """Build a pure train step ``(state, images, *targets) ->
    (state, metrics)`` for any ``loss_call(pred, *targets) ->
    (loss, metrics)`` — the shared mechanics (value_and_grad, optional
    psum-averaging, apply_gradients) for every model family.

    If ``dp_axis`` is given, the step is written for use inside
    ``shard_map``/pjit over that mesh axis: gradients are ``psum``-averaged
    across data-parallel shards (XLA lowers this to an ICI all-reduce).
    Under plain jit with sharded inputs, XLA inserts the same collective
    automatically — pass ``dp_axis=None`` then.
    """

    def step(state: TrainState, images, *targets):
        def loss_fn(params):
            pred = state.apply_fn({"params": params}, images)
            return loss_call(pred, *targets)

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        if dp_axis is not None:
            grads = jax.lax.pmean(grads, dp_axis)
            loss = jax.lax.pmean(loss, dp_axis)
            metrics = jax.lax.pmean(metrics, dp_axis)
        state = state.apply_gradients(grads)
        metrics = {"loss": loss, **metrics}
        return state, metrics

    return step


def make_train_step(dp_axis: str | None = None):
    """Cellpose train step ``(state, images, flows, cellprob) ->
    (state, metrics)`` (see ``make_loss_train_step``)."""
    return make_loss_train_step(cellpose_loss, dp_axis)


@dataclasses.dataclass(frozen=True)
class CellposeConfig:
    features: tuple = (32, 64, 128, 256)
    in_channels: int = 2
    learning_rate: float = 1e-4
    weight_decay: float = 1e-5


def create_model_and_state(
    config: CellposeConfig, rng: jax.Array, input_hw: tuple[int, int] = (256, 256)
) -> tuple[CellposeNet, TrainState]:
    model = CellposeNet(features=config.features, in_channels=config.in_channels)
    params = model.init(
        rng, jnp.zeros((1, *input_hw, config.in_channels), jnp.float32)
    )["params"]
    tx = optax.adamw(config.learning_rate, weight_decay=config.weight_decay)
    return model, TrainState.create(model.apply, params, tx)
