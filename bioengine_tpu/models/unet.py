"""2D U-Net in Flax — the workhorse architecture for BioImage Model Zoo
segmentation models (the reference runs these through bioimageio.core's
torch path, ref apps/model-runner/runtime_deployment.py:234-312).

TPU-first choices:
- NHWC layout (XLA's native conv layout on TPU; feeds the MXU directly).
- GroupNorm instead of BatchNorm: batch-size independent, so the same
  compiled program serves batch 1..N without retraining statistics.
- bf16 compute / f32 params by default; the matmul-heavy convs hit the
  MXU in bf16 while the loss/optimizer stay f32.
- Static pool/upsample factors only — no dynamic shapes inside jit.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


class ConvBlock(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(x)
            x = nn.silu(x)
        return x


class UNet2D(nn.Module):
    """Encoder-decoder with skip connections.

    in: (B, H, W, C_in) with H, W divisible by 2**len(features[:-1]).
    out: (B, H, W, out_channels) logits.
    """

    features: Sequence[int] = (32, 64, 128, 256)
    out_channels: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        for feats in self.features[:-1]:
            x = ConvBlock(feats, self.dtype)(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.features[-1], self.dtype)(x)
        for feats, skip in zip(reversed(self.features[:-1]), reversed(skips)):
            x = nn.ConvTranspose(
                feats, (2, 2), strides=(2, 2), dtype=self.dtype
            )(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock(feats, self.dtype)(x)
        return nn.Conv(self.out_channels, (1, 1), dtype=jnp.float32)(x)

    @property
    def divisor(self) -> int:
        return 2 ** (len(self.features) - 1)
