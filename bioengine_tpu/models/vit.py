"""Vision Transformer embedder (DINOv2-compatible geometry).

The reference embeds cell crops with torch DINOv2 ViT-B/14 at fp16
(ref apps/cell-image-search/embedder.py:40-70, ~500 img/s on one A100).
This is the TPU-native equivalent: a Flax ViT whose weights can be
converted from the torch checkpoint (bioengine_tpu.runtime.convert),
run in bf16 so attention/MLP matmuls tile onto the MXU, and sharded
data-parallel across a pod for corpus embedding.

Attention can route through the Pallas flash kernel for long token
sequences (bioengine_tpu.ops.pallas.attention) or ring attention when
the sequence axis is sharded (bioengine_tpu.parallel.ring).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from flax import linen as nn


class MlpBlock(nn.Module):
    hidden: int
    out: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.gelu(x)
        return nn.Dense(self.out, dtype=self.dtype)(x)


class Attention(nn.Module):
    dim: int
    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    # Optional kernel override: fn(q, k, v) -> out, shapes (B, H, N, d).
    attn_fn: Optional[Callable] = None
    # softmax accumulation dtype; None = follow ``dtype``. In the bf16
    # default this keeps the N^2 tensors half-sized (measured +8-11%
    # end-to-end on v5e at N=257) with embedding fidelity cosine >=
    # 0.9999 vs f32 (tests/test_models.py); pass jnp.float32 for
    # bit-conservative serving at any compute dtype.
    softmax_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        B, N, _ = x.shape
        head_dim = self.dim // self.num_heads
        qkv = nn.Dense(self.dim * 3, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(B, N, 3, self.num_heads, head_dim)
        q, k, v = jnp.moveaxis(qkv, 2, 0)  # each (B, N, H, d)
        q = jnp.swapaxes(q, 1, 2)  # (B, H, N, d)
        k = jnp.swapaxes(k, 1, 2)
        v = jnp.swapaxes(v, 1, 2)
        if self.attn_fn is not None:
            out = self.attn_fn(q, k, v)
        else:
            scale = head_dim**-0.5
            sm_dtype = self.softmax_dtype or self.dtype
            logits = jnp.einsum("bhnd,bhmd->bhnm", q * scale, k)
            weights = nn.softmax(logits.astype(sm_dtype), axis=-1)
            out = jnp.einsum("bhnm,bhmd->bhnd", weights.astype(self.dtype), v)
        out = jnp.swapaxes(out, 1, 2).reshape(B, N, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj")(out)


class Block(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: float = 4.0
    dtype: jnp.dtype = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    softmax_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        # DINOv2 uses pre-norm + LayerScale; gamma converts from torch ls1/ls2.
        y = nn.LayerNorm(dtype=jnp.float32, name="norm1")(x)
        y = Attention(
            self.dim, self.num_heads, self.dtype, self.attn_fn,
            self.softmax_dtype, name="attn",
        )(y)
        y = y * self.param("ls1", nn.initializers.ones, (self.dim,), jnp.float32)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32, name="norm2")(x)
        y = MlpBlock(int(self.dim * self.mlp_ratio), self.dim, self.dtype, name="mlp")(y)
        y = y * self.param("ls2", nn.initializers.ones, (self.dim,), jnp.float32)
        return x + y


class ViT(nn.Module):
    """ViT-B/14 defaults match DINOv2-base (embed 768, 12 heads, 12 blocks)."""

    patch_size: int = 14
    dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    dtype: jnp.dtype = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    softmax_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, images):
        """images: (B, H, W, 3) with H, W divisible by patch_size.

        Returns the CLS embedding (B, dim) in f32 — the similarity-search
        feature vector.
        """
        B, H, W, _ = images.shape
        x = nn.Conv(
            self.dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=self.dtype,
            name="patch_embed",
        )(images.astype(self.dtype))
        n_patches = (H // self.patch_size) * (W // self.patch_size)
        x = x.reshape(B, n_patches, self.dim)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.dim), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.dim)).astype(self.dtype), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, n_patches + 1, self.dim),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = Block(
                self.dim, self.num_heads, self.mlp_ratio, self.dtype,
                self.attn_fn, self.softmax_dtype, name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="norm")(x)
        return x[:, 0].astype(jnp.float32)
