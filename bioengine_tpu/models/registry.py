"""Builtin model registry.

Maps model-zoo style names to Flax module factories. The reference's
analog is the bioimageio collection lookup + torch model load (ref
apps/model-runner/entry_deployment.py:1306-1366); here builtin
architectures are constructed directly and external weights attach via
``bioengine_tpu.runtime.convert``.
"""

from __future__ import annotations

from typing import Any, Callable

from flax import linen as nn

_REGISTRY: dict[str, Callable[..., nn.Module]] = {}


def register_model(name: str):
    def deco(factory: Callable[..., nn.Module]):
        _REGISTRY[name] = factory
        return factory

    return deco


def get_model(name: str, **overrides: Any) -> nn.Module:
    if name not in _REGISTRY:
        raise KeyError(
            f"Unknown model '{name}'. Available: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name](**overrides)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


@register_model("unet2d")
def _unet2d(**kw) -> nn.Module:
    from bioengine_tpu.models.unet import UNet2D

    return UNet2D(**kw)


@register_model("unet3d")
def _unet3d(**kw) -> nn.Module:
    from bioengine_tpu.models.unet3d import UNet3D

    return UNet3D(**kw)


@register_model("cellpose")
def _cellpose(**kw) -> nn.Module:
    from bioengine_tpu.models.cellpose import CellposeNet

    return CellposeNet(**kw)


@register_model("cellpose-sam")
def _cellpose_sam(**kw) -> nn.Module:
    from bioengine_tpu.models.cellpose_sam import CellposeSAM

    return CellposeSAM(**kw)


@register_model("cpsam")
def _cpsam(**kw) -> nn.Module:
    from bioengine_tpu.models.sam import CpSAM

    # global_attn_indexes arrives as a list from YAML/JSON kwargs
    if "global_attn_indexes" in kw:
        kw["global_attn_indexes"] = tuple(kw["global_attn_indexes"])
    return CpSAM(**kw)


@register_model("stardist2d")
def _stardist2d(**kw) -> nn.Module:
    from bioengine_tpu.models.stardist import StarDist2D

    return StarDist2D(**kw)


@register_model("vit-b14")
def _vit_b14(**kw) -> nn.Module:
    from bioengine_tpu.models.vit import ViT

    return ViT(**kw)


@register_model("vit-s14")
def _vit_s14(**kw) -> nn.Module:
    from bioengine_tpu.models.vit import ViT

    kw.setdefault("dim", 384)
    kw.setdefault("num_heads", 6)
    return ViT(**kw)
