"""3D U-Net in Flax — the volumetric member of the BioImage Model Zoo
segmentation family (light-sheet / FIB-SEM / confocal stacks). The
reference executes zoo 3D U-Nets through bioimageio.core's torch path
with blockwise tiling (ref apps/model-runner/runtime_deployment.py:277-280);
here the same family runs jitted on TPU behind the InferenceEngine's
volumetric tiled path (bioengine_tpu/runtime/engine.py).

TPU-first choices (mirrors models/unet.py):
- NDHWC layout: XLA lowers 3D convs to MXU contractions with the
  channel dim innermost, same as 2D.
- GroupNorm, bf16 compute / f32 params, static pool factors.
- Anisotropic option: microscopy stacks usually have coarser z than xy,
  so ``z_strides`` can keep z unpooled at chosen levels (the classic
  anisotropic 3D U-Net recipe) — then the z bucket divisor stays small
  and thin stacks don't over-pad.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


class ConvBlock3D(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(
                self.features, (3, 3, 3), padding="SAME", dtype=self.dtype
            )(x)
            x = nn.GroupNorm(
                num_groups=min(32, self.features), dtype=self.dtype
            )(x)
            x = nn.silu(x)
        return x


class UNet3D(nn.Module):
    """Volumetric encoder-decoder with skip connections.

    in:  (B, D, H, W, C_in) with H, W divisible by ``divisor`` and
         D divisible by ``z_divisor``.
    out: (B, D, H, W, out_channels) logits.

    ``z_strides[i]`` is the z pooling factor at encoder level i
    (1 = keep z resolution at that level — the anisotropic recipe).
    """

    features: Sequence[int] = (16, 32, 64)
    out_channels: int = 1
    z_strides: Sequence[int] | None = None   # default: isotropic (all 2)
    dtype: jnp.dtype = jnp.bfloat16

    def _z_strides(self) -> tuple[int, ...]:
        if self.z_strides is None:
            return tuple(2 for _ in self.features[:-1])
        zs = tuple(int(s) for s in self.z_strides)
        if len(zs) != len(self.features) - 1:
            raise ValueError(
                f"z_strides needs {len(self.features) - 1} entries "
                f"(one per pooling level), got {len(zs)}"
            )
        return zs

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        z_strides = self._z_strides()
        skips = []
        for feats, zs in zip(self.features[:-1], z_strides):
            x = ConvBlock3D(feats, self.dtype)(x)
            skips.append(x)
            x = nn.max_pool(x, (zs, 2, 2), strides=(zs, 2, 2))
        x = ConvBlock3D(self.features[-1], self.dtype)(x)
        for feats, zs, skip in zip(
            reversed(self.features[:-1]),
            reversed(z_strides),
            reversed(skips),
        ):
            x = nn.ConvTranspose(
                feats, (zs, 2, 2), strides=(zs, 2, 2), dtype=self.dtype
            )(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock3D(feats, self.dtype)(x)
        return nn.Conv(self.out_channels, (1, 1, 1), dtype=jnp.float32)(x)

    @property
    def divisor(self) -> int:
        """xy bucket divisor (pooling is always 2x per level in-plane)."""
        return 2 ** (len(self.features) - 1)

    @property
    def z_divisor(self) -> int:
        out = 1
        for zs in self._z_strides():
            out *= zs
        return out
