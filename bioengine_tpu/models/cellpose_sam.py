"""Transformer-backbone Cellpose — the Cellpose-SAM family analog.

The reference fine-tunes *Cellpose-SAM*: a SAM-style ViT image encoder
with a lightweight upsampling head predicting cellpose's 3-channel map
(ref apps/cellpose-finetuning/main.py — its torch train loop wraps the
cpsam torch model). This is the TPU-native member of that family:

- patch embedding + transformer blocks reuse ``models/vit.py``'s
  ``Block`` (bf16 matmuls on the MXU, optional ``attn_fn`` to route
  long-sequence attention through the Pallas flash kernel or ring
  attention when the token axis is sharded over ``sp``),
- 2-D sin-cos positional embeddings computed from the token grid, so
  ANY tile size divisible by ``patch_size`` works without interpolating
  a learned table (fine-tuning tiles differ from inference tiles),
- a progressive ConvTranspose decoder restores full resolution, with
  the cellpose-style global style vector (mean token, L2-normalized)
  modulating each stage,
- same output contract as ``CellposeNet``: (B, H, W, 3) f32 logits
  (flow_y, flow_x, cellprob), so ``cellpose_loss``, ``make_train_step``,
  ``ops/flows`` postprocessing, data-parallel fine-tuning, and the
  model-runner ``jax_params`` path all work unchanged.

Select it in the cellpose-finetuning app with
``config={"backbone": "sam", ...}``.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

from bioengine_tpu.models.vit import Block


def sincos_pos_embed_2d(h: int, w: int, dim: int) -> jnp.ndarray:
    """(h*w, dim) fixed 2-D sin-cos position embedding (half the
    channels encode y, half x)."""
    assert dim % 4 == 0, "pos embed dim must be divisible by 4"
    quarter = dim // 4
    omega = 1.0 / (10000.0 ** (jnp.arange(quarter, dtype=jnp.float32) / quarter))
    ys = jnp.arange(h, dtype=jnp.float32)[:, None] * omega[None, :]  # (h, q)
    xs = jnp.arange(w, dtype=jnp.float32)[:, None] * omega[None, :]  # (w, q)
    y = jnp.concatenate([jnp.sin(ys), jnp.cos(ys)], axis=-1)  # (h, dim/2)
    x = jnp.concatenate([jnp.sin(xs), jnp.cos(xs)], axis=-1)  # (w, dim/2)
    grid = jnp.concatenate(
        [
            jnp.repeat(y[:, None, :], w, axis=1),
            jnp.repeat(x[None, :, :], h, axis=0),
        ],
        axis=-1,
    )  # (h, w, dim)
    return grid.reshape(h * w, dim)


class CellposeSAM(nn.Module):
    """ViT-encoder cellpose: in (B, H, W, C) with H, W divisible by
    ``patch_size``; out (B, H, W, 3) f32 logits."""

    patch_size: int = 8
    dim: int = 256
    depth: int = 8
    num_heads: int = 8
    mlp_ratio: float = 4.0
    in_channels: int = 2
    dtype: jnp.dtype = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    softmax_dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        B, H, W, _ = x.shape
        p = self.patch_size
        gh, gw = H // p, W // p
        x = nn.Conv(
            self.dim, (p, p), strides=(p, p), dtype=self.dtype,
            name="patch_embed",
        )(x.astype(self.dtype))
        x = x.reshape(B, gh * gw, self.dim)
        x = x + sincos_pos_embed_2d(gh, gw, self.dim).astype(self.dtype)[None]
        for i in range(self.depth):
            x = Block(
                self.dim, self.num_heads, self.mlp_ratio, self.dtype,
                self.attn_fn, self.softmax_dtype, name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="norm")(x).astype(self.dtype)

        # cellpose-style global style vector from the token field
        style = jnp.mean(x, axis=1)
        style = style / (
            jnp.linalg.norm(style.astype(jnp.float32), axis=-1, keepdims=True)
            + 1e-6
        ).astype(self.dtype)

        # tokens -> feature map -> progressive 2x decoder back to (H, W)
        x = x.reshape(B, gh, gw, self.dim)
        feats = self.dim
        for stage in range(int(math.log2(p))):
            feats = max(feats // 2, 32)
            x = nn.ConvTranspose(
                feats, (2, 2), strides=(2, 2), dtype=self.dtype,
                name=f"up{stage}",
            )(x)
            x = nn.GroupNorm(
                num_groups=min(32, feats), dtype=self.dtype,
                name=f"up{stage}_norm",
            )(x)
            x = nn.silu(x)
            bias = nn.Dense(feats, dtype=self.dtype, name=f"up{stage}_style")(
                style
            )
            x = x + bias[:, None, None, :]
            x = nn.Conv(
                feats, (3, 3), padding="SAME", dtype=self.dtype,
                name=f"up{stage}_conv",
            )(x)
            x = nn.silu(x)
        x = nn.Conv(3, (1, 1), dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)

    @property
    def divisor(self) -> int:
        # patch grid must tile the input; decoder restores exactly p x
        assert self.patch_size & (self.patch_size - 1) == 0, (
            "patch_size must be a power of two"
        )
        return self.patch_size
