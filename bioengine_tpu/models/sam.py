"""SAM ViT image encoder + cellpose readout — the *pretrained* cpsam
architecture.

The reference's cellpose-finetuning app exists to fine-tune the
pretrained Cellpose-SAM foundation model
(ref apps/cellpose-finetuning/main.py:2248 —
``models.CellposeModel(pretrained_model=...)``, default ``cpsam``;
model_template.py wraps ``cellpose.vit_sam.Transformer``). cpsam is the
segment-anything ViT-L image encoder (patch 8, 256x256 inputs, learned
position embeddings, decomposed relative-position attention, windowed
attention with periodic global blocks, 256-channel neck) with a
transposed-conv readout to cellpose's 3-channel map (flow_y, flow_x,
cellprob logits).

This module is the structurally-faithful flax twin of that public
architecture, so a converted cpsam torch checkpoint
(``runtime.convert.cpsam_name_map``) drops into ``model.init``'s exact
pytree and fine-tuning starts from the foundation weights instead of
random init. Parameter path names below are chosen to line up 1:1 with
the torch state_dict keys — change them only together with the name
map.

TPU notes: attention/matmuls run bf16 on the MXU; the decomposed
rel-pos bias is two small einsums fused by XLA; window partition is a
reshape (no data movement beyond layout). Shapes are static per
(H, W) bucket as everywhere else in the framework.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


def _resize_rel_pos(rel_pos: jnp.ndarray, needed: int) -> jnp.ndarray:
    """(L, head_dim) table -> (needed, head_dim) via linear resize (SAM
    interpolates when query/key extent differs from pretraining)."""
    if rel_pos.shape[0] == needed:
        return rel_pos
    return jax.image.resize(
        rel_pos.astype(jnp.float32),
        (needed, rel_pos.shape[1]),
        method="linear",
    ).astype(rel_pos.dtype)


def _rel_pos_gather(q_size: int, k_size: int, rel_pos: jnp.ndarray):
    """Decomposed relative-position table lookup (SAM's get_rel_pos):
    returns (q_size, k_size, head_dim)."""
    max_dist = 2 * max(q_size, k_size) - 1
    table = _resize_rel_pos(rel_pos, max_dist)
    coords = (
        jnp.arange(q_size)[:, None] * max(k_size / q_size, 1.0)
        - jnp.arange(k_size)[None, :] * max(q_size / k_size, 1.0)
        + (k_size - 1) * max(q_size / k_size, 1.0)
    )
    return table[coords.astype(jnp.int32)]


class SAMAttention(nn.Module):
    """Multi-head attention over a (B, H, W, dim) token grid with SAM's
    decomposed relative position bias.

    ``table_size`` is the PRETRAINING spatial extent the rel-pos tables
    were stored at (window size for windowed blocks, the pretrain grid
    for global ones): the parameters are declared at that checkpoint
    shape — so converted weights always load — and resized at use when
    the runtime grid differs (flax validates provided param shapes
    against the declared shape at apply time)."""

    dim: int
    num_heads: int
    table_size: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, H, W, _ = x.shape
        hd = self.dim // self.num_heads
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(B, H * W, 3, self.num_heads, hd)
        q, k, v = jnp.moveaxis(qkv, 2, 0)  # (B, N, nh, hd)
        q = jnp.moveaxis(q, 2, 1).reshape(B * self.num_heads, H * W, hd)
        k = jnp.moveaxis(k, 2, 1).reshape(B * self.num_heads, H * W, hd)
        v = jnp.moveaxis(v, 2, 1).reshape(B * self.num_heads, H * W, hd)

        attn = (q * (hd**-0.5)) @ jnp.swapaxes(k, -2, -1)  # (B*nh, N, N)

        rel_h = self.param(
            "rel_pos_h",
            nn.initializers.zeros,
            (2 * self.table_size - 1, hd),
            jnp.float32,
        )
        rel_w = self.param(
            "rel_pos_w",
            nn.initializers.zeros,
            (2 * self.table_size - 1, hd),
            jnp.float32,
        )
        Rh = _rel_pos_gather(H, H, rel_h).astype(self.dtype)  # (H, H, hd)
        Rw = _rel_pos_gather(W, W, rel_w).astype(self.dtype)  # (W, W, hd)
        q_r = q.reshape(B * self.num_heads, H, W, hd)
        bias_h = jnp.einsum("bhwc,hkc->bhwk", q_r, Rh)
        bias_w = jnp.einsum("bhwc,wkc->bhwk", q_r, Rw)
        attn = attn.reshape(B * self.num_heads, H, W, H, W)
        attn = attn + bias_h[:, :, :, :, None] + bias_w[:, :, :, None, :]
        attn = attn.reshape(B * self.num_heads, H * W, H * W)

        attn = jax.nn.softmax(attn.astype(jnp.float32), axis=-1).astype(
            self.dtype
        )
        out = (attn @ v).reshape(B, self.num_heads, H * W, hd)
        out = jnp.moveaxis(out, 1, 2).reshape(B, H, W, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj")(out)


def _window_partition(x, ws: int):
    """(B, H, W, C) -> (B*nw, ws, ws, C) with bottom/right padding."""
    B, H, W, C = x.shape
    ph, pw = (-H) % ws, (-W) % ws
    x = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
    Hp, Wp = H + ph, W + pw
    x = x.reshape(B, Hp // ws, ws, Wp // ws, ws, C)
    x = jnp.moveaxis(x, 2, 3).reshape(-1, ws, ws, C)
    return x, (Hp, Wp)


def _window_unpartition(x, ws: int, padded, orig):
    Hp, Wp = padded
    H, W = orig
    B = x.shape[0] // ((Hp // ws) * (Wp // ws))
    x = x.reshape(B, Hp // ws, Wp // ws, ws, ws, -1)
    x = jnp.moveaxis(x, 3, 2).reshape(B, Hp, Wp, -1)
    return x[:, :H, :W]


class SAMBlock(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: float = 4.0
    window_size: int = 0  # 0 = global attention
    table_size: int = 14  # stored rel-pos extent (see SAMAttention)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        shortcut = x
        x = nn.LayerNorm(dtype=jnp.float32, name="norm1")(x).astype(
            self.dtype
        )
        if self.window_size > 0:
            win, padded = _window_partition(x, self.window_size)
            win = SAMAttention(
                self.dim, self.num_heads, self.table_size, self.dtype,
                name="attn",
            )(win)
            x = _window_unpartition(
                win, self.window_size, padded, x.shape[1:3]
            )
        else:
            x = SAMAttention(
                self.dim, self.num_heads, self.table_size, self.dtype,
                name="attn",
            )(x)
        x = shortcut + x
        y = nn.LayerNorm(dtype=jnp.float32, name="norm2")(x).astype(
            self.dtype
        )
        y = nn.Dense(
            int(self.dim * self.mlp_ratio), dtype=self.dtype,
            name="mlp_lin1",
        )(y)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, dtype=self.dtype, name="mlp_lin2")(y)
        return x + y


class SAMEncoder(nn.Module):
    """segment-anything ImageEncoderViT, NHWC. Output: (B, gh, gw, 256)
    neck features at 1/patch resolution."""

    patch_size: int = 8
    dim: int = 1024
    depth: int = 24
    num_heads: int = 16
    mlp_ratio: float = 4.0
    window_size: int = 14
    global_attn_indexes: Sequence[int] = (5, 11, 17, 23)
    neck_dim: int = 256
    pretrain_grid: int = 32  # 256 px / patch 8
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        p = self.patch_size
        B, H, W, _ = x.shape
        gh, gw = H // p, W // p
        x = nn.Conv(
            self.dim, (p, p), strides=(p, p), dtype=self.dtype,
            name="patch_embed",
        )(x.astype(self.dtype))
        pos = self.param(
            "pos_embed",
            nn.initializers.zeros,
            (1, self.pretrain_grid, self.pretrain_grid, self.dim),
            jnp.float32,
        )
        # keyed off the actual table shape (not the attribute) so a
        # checkpoint trained at a different grid still loads and resizes
        if pos.shape[1:3] != (gh, gw):
            pos = jax.image.resize(
                pos, (1, gh, gw, self.dim), method="bilinear"
            )
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            ws = 0 if i in self.global_attn_indexes else self.window_size
            x = SAMBlock(
                self.dim,
                self.num_heads,
                self.mlp_ratio,
                ws,
                # checkpoints store windowed tables at the window extent
                # and global tables at the pretraining grid extent
                table_size=ws if ws > 0 else self.pretrain_grid,
                dtype=self.dtype,
                name=f"block{i}",
            )(x)
        x = nn.Conv(
            self.neck_dim, (1, 1), use_bias=False, dtype=self.dtype,
            name="neck_conv1",
        )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="neck_norm1")(x).astype(
            self.dtype
        )
        x = nn.Conv(
            self.neck_dim, (3, 3), padding="SAME", use_bias=False,
            dtype=self.dtype, name="neck_conv2",
        )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="neck_norm2")(x)
        return x


class CpSAM(nn.Module):
    """cpsam: SAM ViT encoder + transposed-conv readout to cellpose's
    (B, H, W, 3) f32 logits (flow_y, flow_x, cellprob) — same output
    contract as ``CellposeNet``/``CellposeSAM``, so the loss, train
    step, flow postprocessing, and jax_params serving path all work
    unchanged. Input is 3-channel (cpsam convention); the finetuning
    app pads its 2-channel [cyto, nucleus] batches with a zero channel.

    Defaults are ViT-L @ patch 8 — the cpsam checkpoint shape. For
    tests and CI, shrink ``dim/depth/num_heads`` (the name map scales
    with ``depth``)."""

    patch_size: int = 8
    dim: int = 1024
    depth: int = 24
    num_heads: int = 16
    mlp_ratio: float = 4.0
    window_size: int = 14
    global_attn_indexes: Sequence[int] = (5, 11, 17, 23)
    neck_dim: int = 256
    pretrain_grid: int = 32
    in_channels: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        feats = SAMEncoder(
            patch_size=self.patch_size,
            dim=self.dim,
            depth=self.depth,
            num_heads=self.num_heads,
            mlp_ratio=self.mlp_ratio,
            window_size=self.window_size,
            global_attn_indexes=self.global_attn_indexes,
            neck_dim=self.neck_dim,
            pretrain_grid=self.pretrain_grid,
            dtype=self.dtype,
            name="encoder",
        )(x)
        out = nn.ConvTranspose(
            3,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=jnp.float32,
            name="out",
        )(feats.astype(jnp.float32))
        return out

    @property
    def divisor(self) -> int:
        return self.patch_size
