"""StarDist-style star-convex instance segmentation model.

StarDist (Schmidt et al., MICCAI 2018) is, alongside cellpose, the
standard nuclei-segmentation family in the BioImage Model Zoo; the
reference serves zoo StarDist models through its torch/tensorflow
runtime (ref apps/model-runner/runtime_deployment.py:234-312). This is
the TPU-native family member: a UNet2D backbone with two heads —
per-pixel object probability and ``n_rays`` radial boundary distances —
trained/served in bf16 on the MXU. Polygon reconstruction (NMS +
rendering) lives in ``bioengine_tpu.ops.stardist``.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import optax
from flax import linen as nn

from bioengine_tpu.models.unet import ConvBlock


class StarDist2D(nn.Module):
    """in: (B, H, W, C_in); out: (B, H, W, 1 + n_rays) — channel 0 is
    the object-probability logit, channels 1..n_rays are ray distances
    (softplus-activated, in pixels)."""

    n_rays: int = 32
    features: Sequence[int] = (32, 64, 128)
    in_channels: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        for feats in self.features[:-1]:
            x = ConvBlock(feats, self.dtype)(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.features[-1], self.dtype)(x)
        for feats, skip in zip(
            reversed(self.features[:-1]), reversed(skips)
        ):
            x = nn.ConvTranspose(
                feats, (2, 2), strides=(2, 2), dtype=self.dtype
            )(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock(feats, self.dtype)(x)
        prob = nn.Conv(1, (1, 1), dtype=jnp.float32, name="prob_head")(x)
        dist = nn.Conv(
            self.n_rays, (1, 1), dtype=jnp.float32, name="dist_head"
        )(x)
        return jnp.concatenate([prob, nn.softplus(dist)], axis=-1)

    @property
    def divisor(self) -> int:
        return 2 ** (len(self.features) - 1)


def stardist_loss(
    pred: jnp.ndarray,
    prob: jnp.ndarray,
    dist: jnp.ndarray,
    dist_weight: float = 0.2,
):
    """StarDist objective (upstream recipe): BCE on the object
    probability + prob-weighted MAE on ray distances (background rays
    carry no signal and would swamp the regression; weighting by the
    edt target emphasizes rays measured from near the medial axis,
    matching upstream).

    pred: (B, H, W, 1 + n_rays) network output; prob: (B, H, W)
    edt-normalized targets in [0, 1] (``ops.stardist.masks_to_stardist``);
    dist: (B, H, W, n_rays) target ray distances in pixels.
    Consumed by ``make_stardist_train_step``.
    """
    bce = jnp.mean(optax.sigmoid_binary_cross_entropy(pred[..., 0], prob))
    mask = prob[..., None]
    mae = jnp.sum(jnp.abs(pred[..., 1:] - dist) * mask) / (
        jnp.sum(mask) * dist.shape[-1] + 1e-6
    )
    return bce + dist_weight * mae, {"bce_loss": bce, "dist_loss": mae}


def make_stardist_train_step(dp_axis: str | None = None):
    """StarDist train step ``(state, images, prob, dist) ->
    (state, metrics)`` over ``cellpose.TrainState`` — built on the
    shared ``cellpose.make_loss_train_step`` mechanics."""
    from bioengine_tpu.models.cellpose import make_loss_train_step

    return make_loss_train_step(stardist_loss, dp_axis)
