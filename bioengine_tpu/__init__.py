"""BioEngine-TPU — a TPU-native execution layer for bioimage AI.

Built from scratch with the capabilities of aicell-lab/bioengine-worker
(reference: /root/reference), but an idiomatic JAX/XLA/pjit design:

- ``bioengine_tpu.cluster``   — TPU slice provisioning & cluster state
  (replaces the reference's Ray cluster manager, ref bioengine/cluster/).
- ``bioengine_tpu.serving``   — serving controller with health-checked
  replicas pinned to device meshes and continuous batching (replaces
  Ray Serve usage in ref bioengine/apps/).
- ``bioengine_tpu.runtime``   — XLA inference/training runtime with a
  compiled-program cache (replaces the CUDA pipeline cache at ref
  apps/model-runner/runtime_deployment.py:160-232).
- ``bioengine_tpu.parallel``  — mesh/sharding utilities: data-parallel
  pjit training, spatial (halo-exchange) sharding for tiled images,
  ring attention for long token sequences.
- ``bioengine_tpu.apps``      — manifest-driven application system
  (ref bioengine/apps/builder.py + manager.py).
- ``bioengine_tpu.datasets``  — Zarr-over-HTTP dataset streaming with a
  byte-LRU chunk cache and TPU-aware prefetch (ref bioengine/datasets/).
- ``bioengine_tpu.rpc``       — Hypha-compatible WebSocket RPC control
  plane (service registration, per-method ACLs) usable standalone.
- ``bioengine_tpu.worker``    — the BioEngineWorker orchestrator and
  admin code executor (ref bioengine/worker/).
"""

__version__ = "0.1.0"
