from bioengine_tpu.cli.cli import main

main()
