"""``bioengine models`` — builtin architectures + pretrained weight
conversion.

The reference obtains pretrained weights implicitly (cellpose downloads
cpsam, torch.hub downloads DINOv2 — ref
apps/cellpose-finetuning/main.py:2248, apps/cell-image-search/
embedder.py:23-101). The TPU framework makes the step explicit: convert
a torch checkpoint once into the flat-npz ``jax_params`` format every
app consumes (finetuning ``pretrained_path``, embedder
``weights_path``, model-runner ``jax_params`` weight entries).
"""

from __future__ import annotations

import json

import click


@click.group("models")
def models_group() -> None:
    """Builtin model registry and weight conversion."""


@models_group.command("list")
def list_command() -> None:
    """List builtin architecture names (model-runner / rdf registry)."""
    from bioengine_tpu.models.registry import list_models

    click.echo(json.dumps(list_models(), indent=2))


@models_group.command("convert")
@click.argument("checkpoint", type=click.Path(exists=True, dir_okay=False))
@click.argument("output", type=click.Path(dir_okay=False))
@click.option(
    "--arch",
    required=True,
    type=click.Choice(["cpsam", "dinov2"]),
    help="Source checkpoint architecture (name-map family).",
)
@click.option(
    "--depth",
    type=int,
    default=None,
    help="Transformer depth; inferred from the checkpoint when omitted.",
)
@click.option(
    "--no-strict",
    is_flag=True,
    help="Skip (instead of error on) checkpoint keys with no mapping.",
)
def convert_command(checkpoint, output, arch, depth, no_strict) -> None:
    """Convert a torch CHECKPOINT into flat-npz jax_params at OUTPUT.

    Examples: a cpsam download -> `--arch cpsam`; a DINOv2 ViT-B/14
    torch-hub checkpoint -> `--arch dinov2`.
    """
    from bioengine_tpu.runtime.convert import convert_checkpoint, count_params

    params = convert_checkpoint(
        arch, checkpoint, output, depth=depth, strict=not no_strict
    )
    click.echo(
        json.dumps(
            {
                "arch": arch,
                "output": output,
                "n_params": count_params(params),
                "top_level": sorted(params),
            }
        )
    )
