"""CLI helpers: worker connection, value coercion, image I/O, output.

Capability parity with ref bioengine/cli/utils.py:45-210 (service connect
with fallback, typed --arg parsing, npy/npz/png image I/O) minus the S3
helpers (the datasets save API covers that role here).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
from pathlib import Path
from typing import Any, Optional

import click

from bioengine_tpu.rpc.client import ServerConnection, ServiceProxy, connect_to_server

DEFAULT_SERVER_ENV = "BIOENGINE_SERVER_URL"
DEFAULT_TOKEN_ENV = "BIOENGINE_TOKEN"
DEFAULT_WORKSPACE_ENV = "BIOENGINE_WORKSPACE"
WORKER_SERVICE_ID = "bioengine-worker"


def resolve_server_url(server_url: Optional[str]) -> str:
    url = server_url or os.environ.get(DEFAULT_SERVER_ENV)
    if not url:
        raise click.UsageError(
            f"No server URL: pass --server-url or set {DEFAULT_SERVER_ENV}"
        )
    return url


def resolve_token(token: Optional[str]) -> Optional[str]:
    """Token chain: flag > env > the admin token file a colocated worker
    writes into its workspace on startup."""
    if token:
        return token
    env = os.environ.get(DEFAULT_TOKEN_ENV)
    if env:
        return env
    workspace = Path(
        os.environ.get(DEFAULT_WORKSPACE_ENV, "~/.bioengine")
    ).expanduser()
    token_file = workspace / "admin_token"
    if token_file.is_file():
        try:
            return token_file.read_text().strip() or None
        except OSError:
            return None
    return None


async def connect(
    server_url: Optional[str], token: Optional[str] = None
) -> ServerConnection:
    resolved_token = await asyncio.to_thread(resolve_token, token)
    return await connect_to_server(
        {
            "server_url": resolve_server_url(server_url),
            "token": resolved_token,
        }
    )


async def get_worker_service(conn: ServerConnection) -> ServiceProxy:
    return await conn.get_service(WORKER_SERVICE_ID)


def run_async(coro) -> Any:
    return asyncio.run(coro)


def coerce_value(raw: str) -> Any:
    """Auto-type an ``--arg k=v`` value: JSON first, then bare string
    (ref cli/call.py --arg convention)."""
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return raw


def parse_kv_args(pairs: tuple[str, ...]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for pair in pairs:
        if "=" not in pair:
            raise click.UsageError(f"--arg expects k=v, got '{pair}'")
        key, _, value = pair.partition("=")
        out[key] = coerce_value(value)
    return out


def parse_env_args(pairs: tuple[str, ...]) -> dict[str, str]:
    """k=v env vars, values kept as RAW strings — ``--env FLAG=true``
    must reach the app as the literal string "true", not Python True."""
    out: dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise click.UsageError(f"--env expects k=v, got '{pair}'")
        key, _, value = pair.partition("=")
        out[key] = value
    return out


def parse_json_opt(raw: Optional[str], opt_name: str) -> Optional[dict]:
    """Parse a JSON-object option; bad input is a usage error, not a
    traceback."""
    if raw is None:
        return None
    try:
        payload = json.loads(raw)
    except json.JSONDecodeError as e:
        raise click.UsageError(f"{opt_name} is not valid JSON: {e}")
    if not isinstance(payload, dict):
        raise click.UsageError(f"{opt_name} must be a JSON object")
    return payload


def read_dir_files(src_dir: str | Path) -> dict[str, bytes]:
    """Read an app directory into the {relative_path: bytes} wire form
    uploads use (the worker can't see the client's filesystem).

    Hidden files AND files under hidden directories are skipped —
    uploading an app dir that contains ``.git`` must not ship the
    repository object store."""
    src = Path(src_dir)
    return {
        str(p.relative_to(src)): p.read_bytes()
        for p in sorted(src.rglob("*"))
        if p.is_file()
        and not any(
            part.startswith(".") for part in p.relative_to(src).parts
        )
    }


# shared option pair + connection lifecycle for every worker-facing command

_server_opts = [
    click.option("--server-url", default=None, help="Control-plane URL"),
    click.option("--token", default=None, help="Auth token"),
]


def server_options(fn):
    for opt in reversed(_server_opts):
        fn = opt(fn)
    return fn


async def with_worker(server_url, token, action):
    """Connect, resolve the worker service, run ``action(worker)``,
    always disconnect."""
    conn = await connect(server_url, token)
    try:
        worker = await get_worker_service(conn)
        return await action(worker)
    finally:
        await conn.disconnect()


def emit(data: Any, human: Optional[str] = None) -> None:
    """Human text on a TTY, JSON when piped (ref cli/call.py non-TTY)."""
    if sys.stdout.isatty() and human is not None:
        click.echo(human)
    else:
        click.echo(json.dumps(data, indent=2, default=str))


# ---- image I/O (ref cli/utils.py:93-181; tifffile absent -> npy/npz/png) ----


def read_image(path: str | Path):
    import numpy as np

    path = Path(path)
    suffix = path.suffix.lower()
    if suffix == ".npy":
        return np.load(path)
    if suffix == ".npz":
        data = np.load(path)
        return data[next(iter(data.files))]
    if suffix in (".png", ".jpg", ".jpeg", ".tif", ".tiff"):
        from PIL import Image

        return np.asarray(Image.open(path))
    raise click.UsageError(f"Unsupported image format '{suffix}'")


def write_image(path: str | Path, array) -> None:
    import numpy as np

    path = Path(path)
    suffix = path.suffix.lower()
    if suffix == ".npy":
        np.save(path, array)
        return
    if suffix == ".npz":
        np.savez_compressed(path, array)
        return
    if suffix in (".png", ".jpg", ".jpeg"):
        from PIL import Image

        arr = np.asarray(array)
        if arr.dtype != np.uint8:
            lo, hi = float(arr.min()), float(arr.max())
            arr = ((arr - lo) / (hi - lo or 1.0) * 255).astype(np.uint8)
        Image.fromarray(arr).save(path)
        return
    raise click.UsageError(f"Unsupported image format '{suffix}'")
