"""``bioengine call`` — invoke any method on any registered service.

Capability parity with ref bioengine/cli/call.py:48-184: ``--args`` JSON
payload, auto-typed ``--arg k=v`` pairs, image file inputs/outputs
(npy/npz/png), ``--list-methods``, JSON output when stdout is not a TTY.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

import click
import numpy as np

from bioengine_tpu.cli.utils import (
    connect,
    emit,
    parse_json_opt,
    parse_kv_args,
    read_image,
    run_async,
    server_options,
    write_image,
)


@click.command("call")
@click.argument("service_id")
@click.argument("method", required=False)
@click.option("--args", "args_json", default=None, help="JSON kwargs payload")
@click.option(
    "--arg",
    "kv_args",
    multiple=True,
    help="k=v kwarg (JSON-typed value); repeatable",
)
@click.option(
    "--image-arg",
    "image_args",
    multiple=True,
    help="k=path kwarg loaded as an array (npy/npz/png); repeatable",
)
@click.option(
    "--output",
    "output_path",
    default=None,
    type=click.Path(dir_okay=False),
    help="Write an array result to this file instead of printing it",
)
@click.option(
    "--list-methods", is_flag=True, help="List the service's methods and exit"
)
@click.option("--timeout", type=float, default=300.0)
@server_options
def call_command(
    service_id: str,
    method: Optional[str],
    args_json: Optional[str],
    kv_args: tuple[str, ...],
    image_args: tuple[str, ...],
    output_path: Optional[str],
    list_methods: bool,
    timeout: float,
    server_url: Optional[str],
    token: Optional[str],
) -> None:
    """Call METHOD on SERVICE_ID (e.g. `bioengine call demo-app echo
    --arg message=hi`)."""

    async def _run():
        conn = await connect(server_url, token)
        conn.timeout = timeout
        try:
            if list_methods or method is None:
                services = await conn.list_services()
                for info in services:
                    if info["id"] == service_id or info["id"].endswith(
                        f"/{service_id}"
                    ):
                        return {"id": info["id"], "methods": info["methods"]}
                raise click.ClickException(f"Service '{service_id}' not found")
            kwargs = parse_json_opt(args_json, "--args") or {}
            kwargs.update(parse_kv_args(kv_args))
            for pair in image_args:
                if "=" not in pair:
                    raise click.UsageError(
                        f"--image-arg expects k=path, got '{pair}'"
                    )
                key, _, path = pair.partition("=")
                kwargs[key] = await asyncio.to_thread(read_image, path)
            svc = await conn.get_service(service_id)
            return await getattr(svc, method)(**kwargs)
        finally:
            await conn.disconnect()

    result = run_async(_run())
    if list_methods or method is None:
        emit(result, human="\n".join(result["methods"]))
        return
    if output_path is not None:
        array = result
        if isinstance(result, dict):
            arrays = {
                k: v for k, v in result.items() if isinstance(v, np.ndarray)
            }
            if len(arrays) != 1:
                raise click.ClickException(
                    "--output needs an array result (or a dict with exactly "
                    f"one array value; got keys {sorted(result)})"
                )
            array = next(iter(arrays.values()))
        if not isinstance(array, np.ndarray):
            raise click.ClickException("--output needs an array result")
        write_image(output_path, array)
        emit(
            {"saved": output_path, "shape": list(array.shape)},
            human=f"saved {output_path} {array.shape}",
        )
        return
    emit(result, human=json.dumps(result, indent=2, default=str))
