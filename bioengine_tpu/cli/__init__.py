"""Command line interface: ``bioengine call|apps|cluster|status|worker``.

Replaces ref bioengine/cli/ against the framework's own control plane.
"""
