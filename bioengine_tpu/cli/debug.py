"""``bioengine debug`` — incident tooling over the worker's
observability verbs: the cross-host incident bundle, the flight
recorder, and on-demand device profiling of a live deployment.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import click

from bioengine_tpu.cli.utils import emit, run_async, server_options, with_worker


@click.group("debug")
def debug_group() -> None:
    """Incident bundles, flight records, on-demand profiling."""


@debug_group.command("bundle")
@server_options
@click.option(
    "--output",
    "-o",
    default=None,
    help="Artifact path (default: bioengine-debug-<timestamp>.json)",
)
@click.option(
    "--event-limit", default=2000, show_default=True,
    help="Max flight events gathered per process",
)
def bundle_command(server_url, token, output, event_limit):
    """Gather ONE cross-host incident artifact: time-merged flight
    events, recent traces, metrics snapshots, and mesh/lease state
    from the controller and every reachable worker host."""
    result = run_async(
        with_worker(
            server_url,
            token,
            lambda w: w.debug_bundle(event_limit=event_limit),
        )
    )
    path = Path(
        output or f"bioengine-debug-{time.strftime('%Y%m%d-%H%M%S')}.json"
    )
    path.write_text(json.dumps(result, indent=2, default=str))
    hosts = result.get("hosts", {})
    reachable = sum(1 for h in hosts.values() if h.get("reachable"))
    summary = {
        "written": str(path),
        "events": len(result.get("events", [])),
        "traces": len(result.get("traces", [])),
        "hosts_reachable": reachable,
        "hosts_total": len(hosts),
        "dumps": len(result.get("dumps", [])),
    }
    emit(
        summary,
        human=(
            f"incident bundle -> {path}\n"
            f"  {summary['events']} flight events, "
            f"{summary['traces']} trace spans, "
            f"{summary['dumps']} dumps, "
            f"{reachable}/{len(hosts)} hosts reachable"
        ),
    )


@debug_group.command("journal")
@click.option(
    "--dir",
    "control_dir",
    default=None,
    help="Control-plane journal directory "
    "(default: $BIOENGINE_CONTROL_DIR)",
)
@click.option(
    "--tail", default=20, show_default=True,
    help="Journal records to show (newest last)",
)
def journal_command(control_dir, tail):
    """Inspect the controller's durable state OFFLINE: the compacted
    snapshot plus the journal tail (secrets redacted) — the first
    thing the 'Controller loss & upgrade' runbook reads after the
    epoch. Works against a dead controller's directory; no server
    needed."""
    import os

    from bioengine_tpu.serving.journal import ControlJournal

    directory = control_dir or os.environ.get("BIOENGINE_CONTROL_DIR")
    if not directory:
        raise click.UsageError(
            "no journal directory: pass --dir or set BIOENGINE_CONTROL_DIR"
        )
    if not Path(directory).expanduser().exists():
        raise click.UsageError(f"journal directory not found: {directory}")
    info = ControlJournal(directory).inspect(tail=tail)
    snap = info.get("snapshot") or {}
    lines = [
        f"directory: {info['directory']}",
        f"snapshot: epoch={snap.get('epoch', '-')} "
        f"seq={snap.get('seq', '-')} apps={len(snap.get('apps') or {})} "
        f"recovering={snap.get('recovering', False)}"
        if snap
        else "snapshot: (none)",
        f"journal: {info['journal_records']} record(s)"
        + (" — TORN TAIL (truncated final record discarded)"
           if info["torn_tail"] else ""),
    ]
    for app_id, entry in (snap.get("apps") or {}).items():
        deps = ", ".join(
            f"{s.get('name')}x{s.get('num_replicas')}"
            for s in entry.get("specs", [])
        )
        lines.append(f"  app {app_id}: {deps}")
    if info["tail"]:
        lines.append(f"tail (last {len(info['tail'])}):")
        for r in info["tail"]:
            lines.append(
                f"  #{r.get('seq')} "
                f"{time.strftime('%H:%M:%S', time.localtime(r.get('ts', 0)))} "
                f"epoch={r.get('epoch')} {r.get('op')} "
                + json.dumps(r.get("data") or {}, default=str)[:160]
            )
    emit(info, human="\n".join(lines))


@debug_group.command("flight")
@server_options
@click.option("--limit", default=50, show_default=True)
@click.option(
    "--since", default=None, type=float,
    help="Wall-clock cursor: only events at/after this unix time",
)
def flight_command(server_url, token, limit, since):
    """Tail the worker's flight-recorder ring (newest last)."""
    record = run_async(
        with_worker(
            server_url,
            token,
            lambda w: w.get_flight_record(limit=limit, since=since),
        )
    )
    lines = [
        f"{time.strftime('%H:%M:%S', time.localtime(e['ts']))} "
        f"[{e['severity']:7s}] {e['type']:18s} "
        + " ".join(f"{k}={v}" for k, v in e.get("attrs", {}).items())
        for e in record.get("events", [])
    ]
    emit(record, human="\n".join(lines) or "(flight ring is empty)")


@debug_group.command("profile")
@server_options
@click.argument("app_id")
@click.option("--deployment", default=None)
@click.option("--replica", "replica_id", default=None)
@click.option(
    "--action",
    type=click.Choice(["start", "stop", "memory"]),
    default="start",
    show_default=True,
)
@click.option("--trace-dir", default=None)
def profile_command(
    server_url, token, app_id, deployment, replica_id, action, trace_dir
):
    """Profile one replica of a live deployment (jax.profiler on the
    process that runs it; inspect the trace with tensorboard/xprof)."""
    result = run_async(
        with_worker(
            server_url,
            token,
            lambda w: w.profile_replica(
                app_id,
                deployment=deployment,
                replica_id=replica_id,
                action=action,
                trace_dir=trace_dir,
            ),
        )
    )
    if action == "memory":
        # the pprof payload is bytes-heavy; print the per-device stats
        human = json.dumps(
            {k: v for k, v in result.items() if k != "pprof_b64"},
            indent=2,
            default=str,
        )
    else:
        human = json.dumps(result, indent=2, default=str)
    emit(result, human=human)
