"""``bioengine apps`` — application lifecycle from the shell.

Capability parity with ref bioengine/cli/apps.py:91-679: upload, run
(deploy with kwargs/env/ACL), list, status, logs, stop, and the combined
deploy (upload + run). Uploads send FILE CONTENTS over RPC (the
reference's dir→file-list upload), so the worker never needs to see the
client's filesystem.
"""

from __future__ import annotations

import asyncio
import json

import click

from bioengine_tpu.cli.utils import (
    emit,
    parse_env_args,
    parse_json_opt,
    read_dir_files,
    run_async,
    server_options,
    with_worker,
)


@click.group("apps")
def apps_group() -> None:
    """Manage BioEngine applications."""


async def _upload_dir(worker, src_dir, artifact_id=None, version=None) -> dict:
    # bulk file reads off the loop — the RPC connection heartbeats on it
    files = await asyncio.to_thread(read_dir_files, src_dir)
    return await worker.upload_app(
        files=files, artifact_id=artifact_id, version=version
    )


@apps_group.command("upload")
@click.argument("src_dir", type=click.Path(exists=True, file_okay=False))
@click.option("--artifact-id", default=None)
@click.option("--version", default=None)
@server_options
def upload_command(src_dir, artifact_id, version, server_url, token):
    """Upload an app directory to the worker's artifact store."""
    result = run_async(
        with_worker(
            server_url,
            token,
            lambda w: _upload_dir(w, src_dir, artifact_id, version),
        )
    )
    emit(result, human=f"uploaded {result['artifact_id']}@{result['version']}")


@apps_group.command("run")
@click.option("--artifact-id", default=None)
@click.option("--version", default=None)
@click.option(
    "--local-path",
    default=None,
    type=click.Path(exists=True, file_okay=False),
    help="App directory on THIS machine (uploaded, then deployed)",
)
@click.option("--app-id", default=None, help="Reuse an id (update in place)")
@click.option(
    "--deployment-kwargs", default=None, help="JSON {deployment: {kwarg: v}}"
)
@click.option("--env", "env_vars", multiple=True, help="k=v env var; repeatable")
@click.option(
    "--authorized-users", default=None, help="Comma-separated ACL override"
)
@click.option("--auto-redeploy", is_flag=True)
@server_options
def run_command(
    artifact_id,
    version,
    local_path,
    app_id,
    deployment_kwargs,
    env_vars,
    authorized_users,
    auto_redeploy,
    server_url,
    token,
):
    """Deploy an app from an uploaded artifact or a local directory."""
    if not artifact_id and not local_path:
        raise click.UsageError("need --artifact-id or --local-path")
    kwargs = dict(
        artifact_id=artifact_id,
        version=version,
        app_id=app_id,
        deployment_kwargs=parse_json_opt(deployment_kwargs, "--deployment-kwargs"),
        env_vars=parse_env_args(env_vars) or None,
        authorized_users=(
            [u.strip() for u in authorized_users.split(",")]
            if authorized_users
            else None
        ),
        auto_redeploy=auto_redeploy,
    )

    async def action(worker):
        if local_path:
            up = await _upload_dir(worker, local_path)
            kwargs["artifact_id"] = up["artifact_id"]
            kwargs["version"] = up["version"]
        return await worker.deploy_app(**kwargs)

    result = run_async(with_worker(server_url, token, action))
    emit(
        result,
        human=(
            f"deployed {result['app_id']} ({result['name']}) "
            f"methods: {', '.join(result['methods'])}"
        ),
    )


@apps_group.command("list")
@server_options
def list_command(server_url, token):
    """List uploaded app artifacts."""
    result = run_async(with_worker(server_url, token, lambda w: w.list_apps()))
    lines = [
        f"{a['artifact_id']:30s} latest={a['latest']} versions={len(a['versions'])}"
        for a in result
    ]
    emit(result, human="\n".join(lines) or "(no apps)")


def _cold_start_lines(status: dict) -> list[str]:
    """One line per deployment with a cold_start section: warm-pool
    occupancy/promotions, last-replica TTFR, and the compile-tier hit
    rate — the at-a-glance view of whether scale-ups are warm."""
    lines: list[str] = []
    apps = status if "deployments" not in status else {"": status}
    for app_id, st in apps.items():
        for name, dep in (st.get("deployments") or {}).items():
            cold = dep.get("cold_start") or {}
            pool = cold.get("warm_pool")
            ttfr = (cold.get("last_replica_ttfr") or {}).get("ttfr_seconds")
            compile_ = cold.get("compile") or {}
            parts = [f"{app_id + '/' if app_id else ''}{name}:"]
            parts.append(
                f"warm_pool {pool['occupancy']}/{pool['target']} "
                f"(promotions={pool['promotions']})"
                if pool
                else "warm_pool off"
            )
            parts.append(
                f"last_ttfr={ttfr:.3f}s" if ttfr is not None else "last_ttfr=-"
            )
            hr = compile_.get("hit_rate")
            parts.append(
                f"compile_hits={compile_.get('persistent_cache_hits', 0)}"
                f"/{(compile_.get('persistent_cache_hits', 0) or 0) + (compile_.get('real_compiles', 0) or 0)}"
                + (f" ({hr:.0%})" if hr is not None else "")
            )
            lines.append("  ".join(parts))
    return lines


def _mesh_lines(status: dict) -> list[str]:
    """One line per cross-host mesh replica: kind x stages, the hosts
    each shard landed on, and the cross-shard transfer rate — one
    logical deployment over several hosts, readable at a glance."""
    lines: list[str] = []
    apps = status if "deployments" not in status else {"": status}
    for app_id, st in apps.items():
        for name, dep in (st.get("deployments") or {}).items():
            for rid, mesh in (dep.get("cross_host_mesh") or {}).items():
                shards = mesh.get("shards") or []
                placed = ", ".join(
                    f"s{s['stage']}@{s['host_id']}"
                    f"({len(s.get('device_ids') or [])}ch)"
                    for s in shards
                )
                transfer = mesh.get("transfer") or {}
                rate = transfer.get("transfer_bytes_per_sec")
                lines.append(
                    f"{app_id + '/' if app_id else ''}{name} {rid}: "
                    f"{mesh.get('kind')} mesh {mesh.get('mesh_shape')} "
                    f"{'cross-host' if mesh.get('cross_host') else 'one host'}"
                    f" [{placed}]  transfer "
                    f"{transfer.get('transfer_bytes', 0)}B"
                    + (f" @ {rate / 1e6:.1f}MB/s" if rate else "")
                )
    return lines


def _controller_line(status: dict) -> str:
    """The durable-control-plane header: fencing epoch + phase, and —
    while a restarted controller reconciles — the adopt/replace/drop
    counters an operator watches converge."""
    apps = status if "deployments" not in status else {"": status}
    for st in apps.values():
        ctl = (st or {}).get("controller")
        if not ctl:
            continue
        line = f"controller: epoch={ctl.get('epoch')} phase={ctl.get('phase')}"
        rec = ctl.get("reconcile")
        if rec and ctl.get("phase") == "RECOVERING":
            line += (
                f" (reconciling: adopted={rec.get('adopted', 0)} "
                f"replaced={rec.get('replaced', 0)} "
                f"dropped={rec.get('dropped', 0)})"
            )
        return line
    return ""


@apps_group.command("status")
@click.argument("app_id", required=False)
@server_options
def status_command(app_id, server_url, token):
    """Deployment status for one app or all deployed apps."""
    result = run_async(
        with_worker(server_url, token, lambda w: w.get_app_status(app_id=app_id))
    )
    cold = _cold_start_lines(result if isinstance(result, dict) else {})
    mesh = _mesh_lines(result if isinstance(result, dict) else {})
    ctl = _controller_line(result if isinstance(result, dict) else {})
    human = json.dumps(result, indent=2, default=str)
    if mesh:
        human = "mesh:\n" + "\n".join(mesh) + "\n\n" + human
    if cold:
        human = "cold-start:\n" + "\n".join(cold) + "\n\n" + human
    if ctl:
        human = ctl + "\n\n" + human
    emit(result, human=human)


@apps_group.command("logs")
@click.argument("app_id")
@server_options
def logs_command(app_id, server_url, token):
    """Per-replica logs (incl. dead replicas) for an app."""

    async def action(worker):
        status = await worker.get_app_status(app_id=app_id)
        return status.get("replica_logs", {})

    result = run_async(with_worker(server_url, token, action))
    human = []
    for replica, lines in result.items():
        human.append(f"== {replica} ==")
        human.extend(lines if isinstance(lines, list) else [str(lines)])
    emit(result, human="\n".join(human) or "(no logs)")


@apps_group.command("stop")
@click.argument("app_id")
@server_options
def stop_command(app_id, server_url, token):
    """Undeploy an app."""
    result = run_async(
        with_worker(server_url, token, lambda w: w.stop_app(app_id=app_id))
    )
    emit(result, human=f"stopped {result['app_id']}")


@apps_group.command("deploy")
@click.argument("src_dir", type=click.Path(exists=True, file_okay=False))
@click.option("--version", default=None)
@click.option("--auto-redeploy", is_flag=True)
@server_options
def deploy_command(src_dir, version, auto_redeploy, server_url, token):
    """Upload SRC_DIR then deploy it (combined upload + run)."""

    async def action(worker):
        up = await _upload_dir(worker, src_dir, version=version)
        dep = await worker.deploy_app(
            artifact_id=up["artifact_id"],
            version=up["version"],
            auto_redeploy=auto_redeploy,
        )
        return {**up, **dep}

    result = run_async(with_worker(server_url, token, action))
    emit(
        result,
        human=(
            f"deployed {result['app_id']} from "
            f"{result['artifact_id']}@{result['version']}"
        ),
    )
