"""``bioengine cluster`` — cluster state from the shell.

Capability parity with ref bioengine/cli/cluster.py:48-131 (human/JSON
view of the worker's cluster status).
"""

from __future__ import annotations

import click

from bioengine_tpu.cli.utils import emit, run_async, server_options, with_worker


@click.group("cluster")
def cluster_group() -> None:
    """Inspect the worker's compute substrate."""


@cluster_group.command("status")
@server_options
def status_command(server_url, token):
    """Topology, worker processes, and utilization snapshot."""

    async def action(worker):
        status = await worker.get_status()
        return status["cluster"]

    cluster = run_async(with_worker(server_url, token, action))
    topo = cluster.get("topology") or {}
    lines = [
        f"mode:   {cluster.get('mode')}",
        f"ready:  {cluster.get('ready')}",
        f"chips:  {topo.get('n_chips')} x {topo.get('platform')} "
        f"across {topo.get('n_hosts')} host(s)",
        f"workers: {len(cluster.get('workers', []))}",
    ]
    emit(cluster, human="\n".join(lines))
