"""``bioengine cluster`` — cluster state from the shell.

Capability parity with ref bioengine/cli/cluster.py:48-131 (human/JSON
view of the worker's cluster status).
"""

from __future__ import annotations

import click

from bioengine_tpu.cli.utils import emit, run_async, server_options, with_worker


@click.group("cluster")
def cluster_group() -> None:
    """Inspect the worker's compute substrate."""


@cluster_group.command("status")
@server_options
def status_command(server_url, token):
    """Topology, worker processes, and utilization snapshot."""

    async def action(worker):
        status = await worker.get_status()
        return status["cluster"]

    cluster = run_async(with_worker(server_url, token, action))
    topo = cluster.get("topology") or {}
    lines = [
        f"mode:   {cluster.get('mode')}",
        f"ready:  {cluster.get('ready')}",
        f"chips:  {topo.get('n_chips')} x {topo.get('platform')} "
        f"across {topo.get('n_hosts')} host(s)",
        f"workers: {len(cluster.get('workers', []))}",
    ]
    emit(cluster, human="\n".join(lines))


@cluster_group.command("traces")
@click.option("--name", default=None, help="filter by span name")
@click.option("--max-spans", default=30, type=int)
@server_options
def traces_command(name, max_spans, server_url, token):
    """Recent control-plane spans (deploys, replica placements)."""

    async def action(worker):
        return await worker.get_traces(name=name, max_spans=max_spans)

    spans = run_async(with_worker(server_url, token, action))
    lines = [
        f"{s['name']:<16} {s['duration_s']*1000:9.1f} ms  "
        f"{s.get('attrs') or ''}"
        + (f"  ERROR {s['error']}" if s.get("error") else "")
        for s in spans
    ]
    emit(spans, human="\n".join(lines) or "no spans recorded")


@cluster_group.command("profile")
@click.option("--start", "action_name", flag_value="start",
              help="start a jax.profiler trace on the worker")
@click.option("--stop", "action_name", flag_value="stop",
              help="stop the active trace")
@click.option("--memory", "action_name", flag_value="memory",
              help="device-memory snapshot (pprof + per-device stats)")
@click.option("--trace-dir", default=None)
@server_options
def profile_command(action_name, trace_dir, server_url, token):
    """Drive the worker's jax.profiler surface."""
    if action_name is None:
        raise click.UsageError("pass one of --start / --stop / --memory")

    async def action(worker):
        if action_name == "start":
            return await worker.start_profiling(trace_dir=trace_dir)
        if action_name == "stop":
            return await worker.stop_profiling()
        result = await worker.memory_profile()
        # the pprof blob is for files, not terminals
        return {
            "devices": result["devices"],
            "pprof_bytes": len(result["pprof_b64"]) * 3 // 4,
        }

    result = run_async(with_worker(server_url, token, action))
    emit(result, human=str(result))
