"""``bioengine fuzz`` — coverage-guided chaos fuzzing.

Search mode composes fault schedules onto a fuzz topology, scores
novelty, and shrinks any universal-invariant failure to a minimal
replayable JSON artifact (testing/fuzz.py). ``--replay FILE``
re-executes an artifact bit-deterministically and exits non-zero if
the recorded red set no longer reproduces or two replays diverge —
the mode tier-1 uses to hold the regression corpus green.
"""

from __future__ import annotations

import os

import click

from bioengine_tpu.cli.scenarios import _prepare_cpu_devices
from bioengine_tpu.cli.utils import emit


def _quiet_logs() -> None:
    import logging

    # replica/controller lifecycle chatter would drown the verdict
    logging.disable(logging.WARNING)


@click.command("fuzz")
@click.option(
    "--replay",
    "replay_path",
    default=None,
    type=click.Path(exists=True, dir_okay=False),
    help="Re-execute a repro artifact (JSON) instead of searching",
)
@click.option(
    "--corpus",
    "corpus_dir",
    default=None,
    type=click.Path(exists=True, file_okay=False),
    help="Replay every *.json artifact in a directory (regression mode)",
)
@click.option(
    "--topology",
    default="small_multihost",
    show_default=True,
    help="Fuzz substrate (see testing/fuzz.py TOPOLOGIES)",
)
@click.option(
    "--seed",
    default=None,
    type=int,
    help="Search seed [env BIOENGINE_FUZZ_SEED, default 0]",
)
@click.option(
    "--budget-s",
    default=None,
    type=float,
    help="Wall-clock search budget [env BIOENGINE_FUZZ_BUDGET_S, "
    "default 120]",
)
@click.option(
    "--max-runs",
    default=None,
    type=int,
    help="Stop after N schedule executions (besides the time budget)",
)
@click.option(
    "--out",
    "out_dir",
    default=None,
    type=click.Path(file_okay=False),
    help="Directory for shrunk repro artifacts",
)
@click.option(
    "--drill",
    is_flag=True,
    help="Arm the flag-gated lease-accounting drill bug "
    "(BIOENGINE_FUZZ_DRILL=1) — the search MUST find it; exits "
    "non-zero if it does not",
)
@click.option(
    "--keep-going",
    is_flag=True,
    help="Keep searching after a failure instead of stopping at the "
    "first shrunk repro",
)
@click.option(
    "--no-check-determinism",
    is_flag=True,
    help="Replay mode: skip the second run (faster, no determinism gate)",
)
def fuzz_command(
    replay_path,
    corpus_dir,
    topology,
    seed,
    budget_s,
    max_runs,
    out_dir,
    drill,
    keep_going,
    no_check_determinism,
):
    """Coverage-guided fault-schedule search; shrink failures to
    minimal replayable repros (non-zero exit on unexpected failures)."""
    _prepare_cpu_devices()
    _quiet_logs()
    import asyncio

    from bioengine_tpu.testing import fuzz as fuzzer

    if replay_path and corpus_dir:
        raise click.UsageError("--replay and --corpus are exclusive")

    if replay_path or corpus_dir:
        from pathlib import Path

        paths = (
            [Path(replay_path)]
            if replay_path
            else sorted(Path(corpus_dir).glob("*.json"))
        )
        if not paths:
            emit(
                {"replayed": 0},
                human=f"corpus {corpus_dir}: no artifacts — nothing to do",
            )
            return
        check = not no_check_determinism
        rows, lines, failed = [], [], False
        for path in paths:
            verdict = asyncio.run(
                fuzzer.replay_artifact(path, check_determinism=check)
            )
            ok = verdict["matches_expect"] and verdict["deterministic"] in (
                None,
                True,
            )
            failed = failed or not ok
            rows.append(
                {
                    "artifact": str(path),
                    "red": verdict["red"],
                    "matches_expect": verdict["matches_expect"],
                    "deterministic": verdict["deterministic"],
                }
            )
            det = (
                ""
                if verdict["deterministic"] is None
                else (
                    " deterministic"
                    if verdict["deterministic"]
                    else " DIVERGED"
                )
            )
            lines.append(
                f"[{'ok ' if ok else 'FAIL'}] {path.name}: "
                f"red={verdict['red']}{det}"
            )
        emit({"replays": rows}, human="\n".join(lines))
        if failed:
            raise SystemExit(1)
        return

    # ---- search mode ----
    if seed is None:
        seed = int(os.environ.get("BIOENGINE_FUZZ_SEED", "0"))
    if budget_s is None:
        budget_s = float(os.environ.get("BIOENGINE_FUZZ_BUDGET_S", "120"))

    result = asyncio.run(
        fuzzer.fuzz(
            topology=topology,
            seed=seed,
            budget_s=budget_s,
            max_runs=max_runs,
            out_dir=out_dir,
            drill=drill,
            keep_going=keep_going,
            on_progress=lambda msg: click.echo(msg, err=True),
        )
    )
    stats = result["stats"]
    lines = [
        f"fuzz {topology} seed={seed} budget={budget_s:.0f}s"
        f"{' DRILL' if drill else ''}: {stats['runs']} runs, "
        f"{stats['novel']} novel, {stats['failures']} failure(s), "
        f"{stats['shrink_runs']} shrink runs, {stats['elapsed_s']}s",
    ]
    for art, path in zip(
        result["artifacts"],
        result["artifact_paths"] or [None] * len(result["artifacts"]),
    ):
        events = ", ".join(
            f"t{e['at_tick']}:{e['action']}"
            + (f"@{e['host']}" if e.get("host") else "")
            for e in art["events"]
        )
        lines.append(
            f"  repro ({len(art['events'])} event(s)) "
            f"red={art['expect']['red']}: {events}"
        )
        if path:
            lines.append(f"    artifact: {path}")
    emit(
        {"stats": stats, "artifacts": result["artifacts"]},
        human="\n".join(lines),
    )
    if drill and not result["artifacts"]:
        click.echo(
            "DRILL FAILED: the armed lease-leak was not found within "
            "the budget",
            err=True,
        )
        raise SystemExit(1)
    if not drill and result["artifacts"]:
        raise SystemExit(1)
