"""``bioengine scenarios`` — run replayable synthetic incidents.

The scenario engine (bioengine_tpu/testing/scenarios.py) turns the
failure modes production will eventually throw — slow-but-alive
replicas, preemption storms, tenant floods, diurnal waves, connection
blip storms — into seeded, time-compressed, DETERMINISTIC runs against
the in-process multi-host harness, each checked against declarative
invariants. ``run`` executes one (optionally twice, diffing the
outcome sequences — the determinism gate), ``list`` shows the catalog.
"""

from __future__ import annotations

import json
import os
import sys

import click

from bioengine_tpu.cli.utils import emit


def _prepare_cpu_devices() -> None:
    """Scenarios need a few virtual chips per in-process host. On a
    CPU backend, force the same 8-device layout the test suite uses —
    but only while jax is still unimported (the flag is read at
    backend init) and only when no accelerator is expected."""
    if "jax" in sys.modules:
        return
    if os.environ.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


@click.group("scenarios")
def scenarios_group() -> None:
    """Deterministic synthetic incidents (scenario engine)."""


@scenarios_group.command("list")
def scenarios_list_command() -> None:
    """The named-scenario catalog: what each one injects and checks."""
    from bioengine_tpu.testing.scenarios import list_scenarios

    rows = list_scenarios()
    lines = []
    for s in rows:
        topo = (
            f"{s['hosts']}h/{s['replicas']}r"
            if s["hosts"]
            else f"local/{s['replicas']}r"
        )
        sched = " sched" if s["scheduled"] else ""
        lines.append(f"{s['name']:<18} {topo:>9}{sched:<6} {s['description']}")
        if s["faults"]:
            lines.append(
                "                   faults: "
                + ", ".join(
                    f"t{f['tick']}:{f['action']}"
                    + (f"@{f['host']}" if f["host"] else "")
                    for f in s["faults"]
                )
            )
    emit(rows, human="\n".join(lines))


@scenarios_group.command("run")
@click.argument("name")
@click.option("--seed", default=0, show_default=True, help="Workload seed")
@click.option(
    "--no-defenses",
    is_flag=True,
    help="Disable probation + hedging (show the undefended degradation)",
)
@click.option(
    "--check-determinism",
    is_flag=True,
    help="Run twice with the same seed and diff the outcome sequences",
)
@click.option(
    "--out", default=None, help="Write the full result artifact as JSON"
)
def scenarios_run_command(name, seed, no_defenses, check_determinism, out):
    """Run one named scenario and enforce its invariants (non-zero exit
    on any required-invariant failure or a determinism mismatch)."""
    _prepare_cpu_devices()
    import logging

    # replica/controller lifecycle chatter would drown the verdict
    logging.disable(logging.WARNING)
    from bioengine_tpu.testing.scenarios import (
        get_scenario,
        outcome_signature,
        run_scenario,
    )

    scenario = get_scenario(name)
    defenses = not no_defenses
    result = run_scenario(scenario, seed=seed, defenses=defenses)
    runs = [result]
    deterministic = None
    if check_determinism:
        second = run_scenario(scenario, seed=seed, defenses=defenses)
        runs.append(second)
        deterministic = outcome_signature(result) == outcome_signature(second)

    lines = [
        f"scenario {name} seed={seed} defenses={defenses}: "
        f"{'PASS' if result['passed'] else 'FAIL'} "
        f"({result['requests']} requests, {result['counts']})",
        f"  latency p50/p95/p99 ms: "
        f"{result['latency_ms']['p50']}/{result['latency_ms']['p95']}"
        f"/{result['latency_ms']['p99']}  "
        f"probations={result['probations']} hedges={result['hedges']}",
    ]
    for iname, v in result["invariants"].items():
        mark = "ok " if v["ok"] else "FAIL"
        req = "" if v["required"] else " (informational)"
        lines.append(f"  [{mark}] {iname}{req}: {v['detail']}")
    if deterministic is not None:
        lines.append(
            f"  determinism: {'identical' if deterministic else 'DIVERGED'}"
        )

    artifact = {
        "result": {k: v for k, v in result.items() if k != "outcomes"},
        "deterministic": deterministic,
    }
    if out:
        with open(out, "w") as f:
            json.dump({**artifact, "runs": runs}, f, indent=2, default=str)
        lines.append(f"  artifact: {out}")
    emit(artifact, human="\n".join(lines))
    if not result["passed"] or deterministic is False:
        raise SystemExit(1)
