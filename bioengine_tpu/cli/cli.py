"""``bioengine`` CLI root — wires call / apps / cluster / worker / status.

Capability parity with ref bioengine/cli/cli.py:1-62 (click group over
the same three subcommands, plus `worker` to launch a worker and
`status` as a top-level convenience).
"""

from __future__ import annotations

import json

import click

from bioengine_tpu.cli.analyze import analyze_command
from bioengine_tpu.cli.apps import apps_group
from bioengine_tpu.cli.call import call_command
from bioengine_tpu.cli.cluster import cluster_group
from bioengine_tpu.cli.debug import debug_group
from bioengine_tpu.cli.fuzz import fuzz_command
from bioengine_tpu.cli.models import models_group
from bioengine_tpu.cli.scenarios import scenarios_group
from bioengine_tpu.cli.slo import slo_group, top_command


@click.group()
@click.version_option(package_name="bioengine-tpu", prog_name="bioengine")
def main() -> None:
    """BioEngine-TPU command line interface."""


main.add_command(analyze_command)
main.add_command(call_command)
main.add_command(apps_group)
main.add_command(cluster_group)
main.add_command(debug_group)
main.add_command(fuzz_command)
main.add_command(models_group)
main.add_command(scenarios_group)
main.add_command(slo_group)
main.add_command(top_command)


@main.command("status")
@click.option("--server-url", default=None, help="Control-plane URL")
@click.option("--token", default=None, help="Auth token")
def status_command(server_url, token):
    """Full worker status (worker / cluster / applications / datasets)."""
    from bioengine_tpu.cli.utils import emit, run_async, with_worker

    result = run_async(
        with_worker(server_url, token, lambda w: w.get_status())
    )
    emit(result, human=json.dumps(result, indent=2, default=str))


@main.command(
    "worker",
    context_settings={"ignore_unknown_options": True, "help_option_names": []},
)
@click.argument("worker_args", nargs=-1, type=click.UNPROCESSED)
def worker_command(worker_args):
    """Start a worker (forwards args to `python -m bioengine_tpu.worker`)."""
    from bioengine_tpu.worker.__main__ import main as worker_main

    worker_main(list(worker_args))


if __name__ == "__main__":
    main()
