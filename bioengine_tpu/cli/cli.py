"""``bioengine`` CLI entry point (subcommands land with the CLI milestone)."""

from __future__ import annotations

import click


@click.group()
def main() -> None:
    """BioEngine-TPU command line interface."""


if __name__ == "__main__":
    main()
