"""``bioengine slo`` / ``bioengine top`` — fleet questions answered
from the controller's telemetry history and SLO engine: is every
deployment meeting its objectives, how fast is each burning its error
budget, and what is the fleet doing right now.
"""

from __future__ import annotations

import time

import click

from bioengine_tpu.cli.utils import emit, run_async, server_options, with_worker


def _fmt(value, unit: str = "", width: int = 9, digits: int = 1) -> str:
    if value is None:
        return "-".rjust(width)
    return f"{value:.{digits}f}{unit}".rjust(width)


def _alert_flag(alert) -> str:
    if not alert or alert.get("state") in (None, "inactive"):
        return "ok"
    state = alert["state"]
    if state == "resolved":
        return "resolved"
    return f"{state}({alert.get('severity')})"


@click.group("slo")
def slo_group() -> None:
    """Service objectives: burn rates, budgets, alert state."""


@slo_group.command("status")
@server_options
@click.option("--app", default=None, help="Filter to one app id")
def slo_status_command(server_url, token, app):
    """Per-deployment SLO status: burn rates over every rule window,
    error budget remaining, alert lifecycle state, and any
    auto-captured incident bundles."""
    result = run_async(
        with_worker(server_url, token, lambda w: w.get_slo_status())
    )
    lines = []
    deployments = result.get("deployments", {})
    for key, status in sorted(deployments.items()):
        if app is not None and not key.startswith(f"{app}/"):
            continue
        lines.append(f"{key}  (burn_pressure={status.get('burn_pressure')})")
        for objective, o in sorted(status.get("objectives", {}).items()):
            alert = o.get("alert") or {}
            target = o.get("target")
            head = (
                f"latency p{target} < {o.get('latency_objective_ms')}ms"
                if objective == "latency"
                else f"availability {target}%"
            )
            lines.append(
                f"  {objective:12s} {head:32s} "
                f"budget_remaining={o.get('budget_remaining')} "
                f"alert={_alert_flag(alert)} "
                f"burn_short={alert.get('burn_short', 0.0)} "
                f"burn_long={alert.get('burn_long', 0.0)}"
            )
    for b in result.get("auto_bundles", []):
        a = b.get("alert") or {}
        lines.append(
            f"  auto-bundle @{b.get('generated_at')}: "
            f"{a.get('app')}/{a.get('deployment')} {a.get('objective')} "
            f"({b.get('events')} events)"
        )
    if not lines:
        lines = ["(no deployments carry an slo: block)"]
    emit(result, human="\n".join(lines))


@click.command("top")
@server_options
@click.option(
    "--watch", default=0, show_default=True,
    help="Refresh every N seconds (0 = print once)",
)
@click.option(
    "--since-s", default=300.0, show_default=True,
    help="History window to summarize (seconds)",
)
def top_command(server_url, token, watch, since_s):
    """Fleet overview: per-deployment request/error rates, latency
    quantiles, queue depth, chip-seconds, and SLO alert state — the
    controller's telemetry store rendered as one table."""

    async def fetch(w):
        # wall-clock CURSOR (the store keys history by wall time), not
        # a duration  # bioengine: ignore[BE-OBS-001]
        since = time.time() - since_s
        telem = await w.get_telemetry(since=since)
        slo = await w.get_slo_status()
        return {"telemetry": telem, "slo": slo}

    def render(result) -> str:
        telem = result["telemetry"]
        slo_by_dep = result["slo"].get("deployments", {})
        header = (
            f"{'deployment':28s} {'req/s':>9s} {'err/s':>9s} "
            f"{'p50 ms':>9s} {'p99 ms':>9s} {'queue':>7s} "
            f"{'chip s':>9s} {'shed/s':>9s}  slo"
        )
        rows = [header, "-" * len(header)]

        def latest(points):
            for p in reversed(points or []):
                if p.get("value") is not None:
                    return p["value"]
            return None

        for key, series in sorted(telem.get("deployments", {}).items()):
            alerts = [
                _alert_flag(o.get("alert"))
                for o in slo_by_dep.get(key, {}).get("objectives", {}).values()
            ]
            # top's column answers "needs attention NOW" — a recently
            # recovered alert shows its resolved badge but a fleet scan
            # must not read it as unhealthy (slo status keeps the detail)
            firing = [a for a in alerts if a not in ("ok", "resolved")]
            p50 = latest(series.get("latency_p50"))
            p99 = latest(series.get("latency_p99"))
            rows.append(
                f"{key:28s} "
                f"{_fmt(latest(series.get('request_rate')))} "
                f"{_fmt(latest(series.get('error_rate')), digits=2)} "
                f"{_fmt(p50 * 1000.0 if p50 is not None else None)} "
                f"{_fmt(p99 * 1000.0 if p99 is not None else None)} "
                f"{_fmt(latest(series.get('queue_depth')), width=7, digits=0)} "
                f"{_fmt(latest(series.get('chip_seconds')), digits=2)} "
                f"{_fmt(latest(series.get('shed_rate')), digits=2)}  "
                + (",".join(firing) if firing else "ok")
            )
        if len(rows) == 2:
            rows.append("(no telemetry history yet)")
        store = telem.get("store", {})
        rows.append(
            f"\nstore: {store.get('series')} series, hosts pushing: "
            f"{sorted((store.get('hosts') or {}))}"
        )
        return "\n".join(rows)

    result = run_async(with_worker(server_url, token, fetch))
    if not watch:
        emit(result, human=render(result))
        return
    try:
        while True:
            click.clear()
            click.echo(render(result))
            time.sleep(watch)
            result = run_async(with_worker(server_url, token, fetch))
    except KeyboardInterrupt:
        pass
