"""``bioengine analyze`` — CLI front-end for the static analyzer.

Thin pass-through to :mod:`bioengine_tpu.analysis.__main__` so the
click command and ``python -m bioengine_tpu.analysis`` share one
argument surface and exit-code contract (0 clean, 1 findings,
2 usage error).  Unknown options forward verbatim, so new analyzer
flags never need a second wiring here.
"""

from __future__ import annotations

import sys

import click


@click.command(
    "analyze",
    context_settings={"ignore_unknown_options": True, "help_option_names": []},
)
@click.argument("analyzer_args", nargs=-1, type=click.UNPROCESSED)
def analyze_command(analyzer_args: tuple[str, ...]) -> None:
    """Run the whole-program linter (async-safety, JAX tracer-safety,
    distributed-contract drift).

    Examples:

      bioengine analyze bioengine_tpu/ apps/

      bioengine analyze --changed origin/main

      bioengine analyze --format sarif --stats --jobs 8

      bioengine analyze --list-rules
    """
    from bioengine_tpu.analysis.__main__ import main as analysis_main

    sys.exit(analysis_main(list(analyzer_args)))
