"""Worker provisioners — the TPU answer to the reference's SLURM GPU
autoscaler (ref bioengine/cluster/slurm_workers.py).

A provisioner turns *pending workload pressure* into worker capacity:

- ``SlurmProvisioner`` submits sbatch jobs that start a BioEngine-TPU
  host process on a TPU partition node. Reproduces the reference's
  policy: scale UP when pending workloads exist, sized from the pending
  item's resource request, bounded by max_workers and a cooldown
  (ref slurm_workers.py:688-774); scale DOWN a worker only after it is
  idle across the whole recent status-history window
  (ref slurm_workers.py:817-903).
- ``GkeProvisioner`` targets GCP queued-resources / GKE node pools for
  real TPU slices (same policy, different backend verbs).
- ``NullProvisioner`` for single-machine / external modes.

Command execution goes through an injectable runner so policy is
hermetically testable without sbatch/gcloud.
"""

from __future__ import annotations

import abc
import subprocess
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from bioengine_tpu.utils.logger import create_logger


@dataclass
class WorkerRecord:
    worker_id: str
    backend_job_id: str
    submitted_at: float
    resources: dict[str, float]
    state: str = "pending"          # pending | running | draining | gone
    # the tag the launched worker_host reports on join (--worker-tag);
    # ClusterState.HostRecord.worker_tag carries it back, so an idle
    # JOINED host can be mapped to the backend job to cancel (the
    # reference correlates via a slurm_job_id custom Ray resource,
    # ref slurm_workers.py:645-664)
    worker_tag: Optional[str] = None


@dataclass
class ScalingPolicy:
    max_workers: int = 4
    cooldown_seconds: float = 60.0
    idle_window_snapshots: int = 12   # consecutive idle snapshots before down
    default_resources: dict = field(
        default_factory=lambda: {"chips": 8, "cpus": 16, "memory_gb": 64}
    )


CommandRunner = Callable[[list[str]], "subprocess.CompletedProcess"]


def _real_runner(cmd: list[str]) -> subprocess.CompletedProcess:
    return subprocess.run(cmd, capture_output=True, text=True, timeout=60)


class Provisioner(abc.ABC):
    def __init__(self, policy: Optional[ScalingPolicy] = None):
        self.policy = policy or ScalingPolicy()
        self.workers: dict[str, WorkerRecord] = {}
        self._last_scale_up = float("-inf")  # monotonic clock
        self.join_server_url: Optional[str] = None
        self.join_token: Optional[str] = None
        self.logger = create_logger(self.__class__.__name__, log_file="off")

    def set_join_info(self, server_url: str, token: str) -> None:
        """Where provisioned worker_host processes should join: the
        controller's RPC url + an admin token. Embedded into launch
        scripts (the reference embeds the head node's Ray address the
        same way, ref slurm_workers.py:153-296)."""
        self.join_server_url = server_url
        self.join_token = token

    # -- backend verbs --------------------------------------------------------

    @abc.abstractmethod
    def _submit(self, resources: dict[str, float], worker_tag: str) -> str:
        """Start one worker carrying ``worker_tag``; return a backend
        job id."""

    @abc.abstractmethod
    def _cancel(self, backend_job_id: str) -> None: ...

    @abc.abstractmethod
    def _poll_state(self, backend_job_id: str) -> str:
        """'pending' | 'running' | 'gone'"""

    # -- policy ---------------------------------------------------------------

    def check_scaling(
        self,
        pending: list,
        history: list[dict],
        idle_worker_ids: Optional[set[str]] = None,
    ) -> dict:
        """One policy tick. Returns {"scaled_up": [...], "scaled_down": [...]}."""
        self._refresh_states()
        up, down = [], []
        active = [
            w for w in self.workers.values() if w.state in ("pending", "running")
        ]
        # Scale up: pending workloads + cooldown elapsed + below cap.
        if (
            pending
            and time.monotonic() - self._last_scale_up > self.policy.cooldown_seconds
            and len(active) < self.policy.max_workers
        ):
            item = pending[0]
            resources = dict(self.policy.default_resources)
            req = getattr(item, "resources", None) or {}
            resources.update({k: v for k, v in req.items() if v})
            worker_id = f"worker-{uuid.uuid4().hex[:8]}"
            worker_tag = worker_id.removeprefix("worker-")
            job_id = self._submit(resources, worker_tag)
            self.workers[worker_id] = WorkerRecord(
                worker_id=worker_id,
                backend_job_id=job_id,
                submitted_at=time.time(),
                resources=resources,
                worker_tag=worker_tag,
            )
            self._last_scale_up = time.monotonic()
            up.append(worker_id)
            self.logger.info(
                f"scale-up {worker_id} (job {job_id}) for pending "
                f"{getattr(item, 'workload_id', item)}"
            )
        # Scale down: a worker idle across the WHOLE recent window and no
        # pending demand. ``idle_worker_ids`` intersects per-snapshot idle
        # sets computed by the caller (the reference intersects idle-node
        # sets across its status history, slurm_workers.py:817-903).
        if not pending and idle_worker_ids:
            window = history[-self.policy.idle_window_snapshots :]
            if len(window) >= self.policy.idle_window_snapshots:
                for worker_id in sorted(idle_worker_ids):
                    w = self.workers.get(worker_id)
                    if w and w.state == "running":
                        self._cancel(w.backend_job_id)
                        w.state = "gone"
                        down.append(worker_id)
                        self.logger.info(f"scale-down {worker_id}")
        return {"scaled_up": up, "scaled_down": down}

    def _refresh_states(self) -> None:
        for w in self.workers.values():
            if w.state in ("pending", "running"):
                w.state = self._poll_state(w.backend_job_id)

    def close_all(self) -> None:
        for w in self.workers.values():
            if w.state in ("pending", "running"):
                try:
                    self._cancel(w.backend_job_id)
                except Exception as e:
                    self.logger.warning(f"cancel {w.worker_id}: {e}")
                w.state = "gone"
        # close_all is terminal teardown: drop the records too, or the
        # registry grows one dead entry per worker ever provisioned
        self.workers.clear()

    def active_workers(self) -> list[WorkerRecord]:
        return [
            w for w in self.workers.values() if w.state in ("pending", "running")
        ]


class NullProvisioner(Provisioner):
    """single-machine / external-cluster modes: capacity is fixed."""

    def _submit(self, resources, worker_tag):  # pragma: no cover - never called
        raise RuntimeError("NullProvisioner cannot scale")

    def _cancel(self, backend_job_id):
        pass

    def _poll_state(self, backend_job_id):
        return "gone"

    def check_scaling(self, pending, history, idle_worker_ids=None):
        return {"scaled_up": [], "scaled_down": []}


class SlurmProvisioner(Provisioner):
    """sbatch-backed workers on an HPC TPU/accelerator partition."""

    def __init__(
        self,
        partition: str = "tpu",
        time_limit: str = "4:00:00",
        worker_command: str = "python -m bioengine_tpu.worker_host",
        container_image: Optional[str] = None,
        extra_sbatch_args: str = "",
        policy: Optional[ScalingPolicy] = None,
        runner: CommandRunner = _real_runner,
    ):
        super().__init__(policy)
        self.partition = partition
        self.time_limit = time_limit
        self.worker_command = worker_command
        self.container_image = container_image
        self.extra_sbatch_args = extra_sbatch_args
        self.runner = runner

    def build_sbatch_script(self, resources: dict[str, float], worker_tag: str) -> str:
        """The launch script: starts a bioengine host process that joins
        the cluster, tagged so a targeted shutdown can find it (the
        reference tags Ray workers with a slurm_job_id custom resource,
        ref slurm_workers.py:153-296)."""
        cmd = f"{self.worker_command} --worker-tag {worker_tag}"
        if self.container_image:
            cmd = (
                f"apptainer exec --bind $PWD {self.container_image} {cmd}"
            )
        cpus = int(resources.get("cpus", 8))
        mem = int(resources.get("memory_gb", 32))
        join_env = []
        if self.join_server_url:
            join_env.append(
                f"export BIOENGINE_SERVER_URL={self.join_server_url}"
            )
        if self.join_token:
            join_env.append(f"export BIOENGINE_ADMIN_TOKEN={self.join_token}")
        return "\n".join(
            [
                "#!/bin/bash",
                f"#SBATCH --job-name=bioengine-{worker_tag}",
                f"#SBATCH --partition={self.partition}",
                f"#SBATCH --cpus-per-task={cpus}",
                f"#SBATCH --mem={mem}G",
                f"#SBATCH --time={self.time_limit}",
                *(
                    [f"#SBATCH {self.extra_sbatch_args}"]
                    if self.extra_sbatch_args
                    else []
                ),
                "set -euo pipefail",
                *join_env,
                f"exec {cmd}",
            ]
        )

    def _submit(self, resources: dict[str, float], worker_tag: str) -> str:
        import tempfile

        script = self.build_sbatch_script(resources, worker_tag)
        with tempfile.NamedTemporaryFile(
            "w", suffix=".sbatch", prefix="bioengine-", delete=False
        ) as f:
            f.write(script)
            script_path = f.name
        proc = self.runner(["sbatch", "--parsable", script_path])
        if proc.returncode != 0:
            raise RuntimeError(f"sbatch failed: {proc.stderr}")
        return proc.stdout.strip().split(";")[0]

    def _cancel(self, backend_job_id: str) -> None:
        self.runner(["scancel", backend_job_id])

    def _poll_state(self, backend_job_id: str) -> str:
        proc = self.runner(
            ["squeue", "-j", backend_job_id, "-h", "-o", "%T"]
        )
        state = proc.stdout.strip().upper()
        if not state:
            return "gone"
        if state in ("PENDING", "CONFIGURING"):
            return "pending"
        if state in ("RUNNING", "COMPLETING"):
            return "running"
        return "gone"


class GkeProvisioner(Provisioner):
    """GCP queued-resources backed TPU slices (gcloud CLI).

    Uses ``gcloud compute tpus queued-resources`` verbs; requires gcloud
    auth on the controller host. Policy identical to SLURM.
    """

    def __init__(
        self,
        project: str,
        zone: str,
        accelerator_type: str = "v5litepod-8",
        runtime_version: str = "v2-alpha-tpuv5-lite",
        worker_command: str = "python -m bioengine_tpu.worker_host",
        policy: Optional[ScalingPolicy] = None,
        runner: CommandRunner = _real_runner,
    ):
        super().__init__(policy)
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.worker_command = worker_command
        self.runner = runner

    def build_startup_script(self, worker_tag: str) -> str:
        """What the TPU VM runs on boot: join THIS control plane as a
        worker host, tagged for targeted scale-down. Without this the
        provisioned node would sit idle forever — the GKE analog of the
        sbatch script's join env (ref slurm_workers.py:153-296)."""
        lines = ["#!/bin/bash", "set -euo pipefail"]
        if self.join_server_url:
            lines.append(
                f"export BIOENGINE_SERVER_URL={self.join_server_url}"
            )
        if self.join_token:
            lines.append(f"export BIOENGINE_ADMIN_TOKEN={self.join_token}")
        lines.append(
            f"exec {self.worker_command} --worker-tag {worker_tag}"
        )
        return "\n".join(lines)

    def _submit(self, resources: dict[str, float], worker_tag: str) -> str:
        name = f"bioengine-{worker_tag}"
        startup = self.build_startup_script(worker_tag)
        proc = self.runner(
            [
                "gcloud", "compute", "tpus", "queued-resources", "create",
                name,
                f"--project={self.project}",
                f"--zone={self.zone}",
                f"--accelerator-type={self.accelerator_type}",
                f"--runtime-version={self.runtime_version}",
                f"--node-id={name}",
                f"--metadata=startup-script={startup}",
            ]
        )
        if proc.returncode != 0:
            raise RuntimeError(f"queued-resources create failed: {proc.stderr}")
        return name

    def _cancel(self, backend_job_id: str) -> None:
        self.runner(
            [
                "gcloud", "compute", "tpus", "queued-resources", "delete",
                backend_job_id,
                f"--project={self.project}",
                f"--zone={self.zone}",
                "--quiet", "--force",
            ]
        )

    def _poll_state(self, backend_job_id: str) -> str:
        proc = self.runner(
            [
                "gcloud", "compute", "tpus", "queued-resources", "describe",
                backend_job_id,
                f"--project={self.project}",
                f"--zone={self.zone}",
                "--format=value(state.state)",
            ]
        )
        state = proc.stdout.strip().upper()
        if not state or proc.returncode != 0:
            return "gone"
        if state in ("WAITING_FOR_RESOURCES", "CREATING", "ACCEPTED", "PROVISIONING"):
            return "pending"
        if state == "ACTIVE":
            return "running"
        return "gone"
