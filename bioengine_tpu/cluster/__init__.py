from bioengine_tpu.cluster.cluster import TpuCluster
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology, detect_topology

__all__ = ["TpuCluster", "ClusterState", "TpuTopology", "detect_topology"]
