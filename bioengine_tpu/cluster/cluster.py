"""TpuCluster — the compute-substrate manager.

The reference's RayCluster starts/attaches a Ray head, holds a lock
file with stale-PID detection, keeps a status-history ring, and drives
the SLURM autoscaler from its monitor loop (ref bioengine/cluster/
ray_cluster.py:158-163 modes, :394-478 lock, :844-861 history). Here
there is no external cluster runtime to babysit: the substrate is the
JAX-visible TPU topology plus optional provisioned workers, so this
class owns

- the workspace lock (one cluster manager per workspace dir, stale PIDs
  reclaimed),
- topology detection + the ClusterState service,
- the provisioner for ``slurm`` / ``gke`` modes (``single-machine`` and
  ``external`` use NullProvisioner),
- the monitor tick: snapshot -> scaling decision, mirroring
  ref ray_cluster.py monitor_cluster + slurm check_scaling.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional

from bioengine_tpu.cluster.provisioner import (
    GkeProvisioner,
    NullProvisioner,
    Provisioner,
    SlurmProvisioner,
)
from bioengine_tpu.cluster.state import ClusterState
from bioengine_tpu.cluster.topology import TpuTopology, detect_topology
from bioengine_tpu.utils.logger import create_logger

MODES = ("single-machine", "slurm", "gke", "external")


class ClusterLockError(RuntimeError):
    pass


class TpuCluster:
    def __init__(
        self,
        mode: str = "single-machine",
        workspace_dir: str | Path = "~/.bioengine",
        provisioner: Optional[Provisioner] = None,
        provisioner_config: Optional[dict] = None,
        log_file: Optional[str] = None,
        topology: Optional[TpuTopology] = None,
    ):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got '{mode}'")
        self.mode = mode
        self.workspace_dir = Path(workspace_dir).expanduser()
        self.logger = create_logger("cluster", log_file=log_file)
        self._lock_path = self.workspace_dir / "cluster.lock"
        self._locked = False
        self._topology = topology
        self.state: Optional[ClusterState] = None
        self.provisioner = provisioner or self._make_provisioner(
            provisioner_config or {}
        )
        self.is_ready = False

    def _make_provisioner(self, cfg: dict) -> Provisioner:
        if self.mode == "slurm":
            return SlurmProvisioner(**cfg)
        if self.mode == "gke":
            return GkeProvisioner(**cfg)
        return NullProvisioner()

    # ---- lock file (one manager per workspace) ------------------------------

    def _acquire_lock(self) -> None:
        self.workspace_dir.mkdir(parents=True, exist_ok=True)
        if self._lock_path.exists():
            try:
                pid = int(self._lock_path.read_text().strip() or "0")
            except ValueError:
                pid = 0
            if pid and _pid_alive(pid):
                raise ClusterLockError(
                    f"Workspace {self.workspace_dir} is managed by live "
                    f"process {pid} (remove {self._lock_path} if stale)"
                )
            self.logger.warning(
                f"Reclaiming stale cluster lock (pid {pid} is gone)"
            )
            self._lock_path.unlink()
        fd = os.open(self._lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        with os.fdopen(fd, "w") as f:
            f.write(str(os.getpid()))
        self._locked = True

    def _release_lock(self) -> None:
        if self._locked and self._lock_path.exists():
            self._lock_path.unlink()
        self._locked = False

    # ---- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._acquire_lock()
        try:
            topo = self._topology or detect_topology()
            self._topology = topo
            self.state = ClusterState(topo)
            self.state.snapshot()
            self.logger.info(
                f"Cluster up ({self.mode}): {topo.n_chips} "
                f"{topo.platform} chip(s) across {topo.n_hosts} host(s)"
            )
            self.is_ready = True
        except Exception:
            self._release_lock()
            raise

    def stop(self) -> None:
        self.is_ready = False
        try:
            self.provisioner.close_all()
        finally:
            self._release_lock()
        self.logger.info("Cluster stopped")

    def check_connection(self) -> bool:
        """Cheap liveness: can we still enumerate devices?"""
        if not self.is_ready or self.state is None:
            return False
        try:
            return self.state.topology.n_chips > 0
        except Exception:
            return False

    # ---- monitor tick -------------------------------------------------------

    def monitor_cluster(self) -> dict:
        """One monitoring tick: snapshot + scaling decision."""
        if self.state is None:
            raise RuntimeError("cluster not started")
        self.state.snapshot()
        idle_workers = self._idle_worker_ids()
        actions = self.provisioner.check_scaling(
            self.state.pending(), self.state.history(), idle_workers
        )
        for workload in list(self.state.pending()):
            # pending items are cleared by the serving controller once
            # placed; stale ones older than an hour are dropped here.
            # submitted_at is a displayed wall timestamp; an hour-scale
            # staleness gate tolerates NTP slew.
            # bioengine: ignore[BE-OBS-001]
            if time.time() - workload.submitted_at > 3600:
                self.state.remove_pending(workload.workload_id)
        return actions

    def _idle_worker_ids(self) -> set[str]:
        """Workers eligible for scale-down.

        Per-host idleness: a joined host with no live replica leased to
        it maps back to its backend job through the ``worker_tag`` it
        reported on join (the reference correlates idle Ray nodes to
        SLURM jobs the same way, ref slurm_workers.py:817-903). Workers
        whose host never joined stay un-cancellable here — the
        provisioner's own state polling reaps jobs that died before
        joining."""
        if self.state is None:
            return set()
        live_hosts = {
            r.host_id for r in self.state.replicas() if r.alive
        }  # may contain None = the controller host itself
        tag_to_worker = {
            w.worker_tag: w.worker_id
            for w in self.provisioner.active_workers()
            if w.worker_tag
        }
        idle = set()
        for host in self.state.hosts.values():
            if not host.alive or host.host_id in live_hosts:
                continue
            worker_id = tag_to_worker.get(host.worker_tag)
            if worker_id:
                idle.add(worker_id)
        return idle

    @property
    def status(self) -> dict:
        return {
            "mode": self.mode,
            "ready": self.is_ready,
            "topology": self._topology.as_dict() if self._topology else None,
            "workers": [
                {
                    "worker_id": w.worker_id,
                    "state": w.state,
                    "resources": w.resources,
                }
                for w in self.provisioner.workers.values()
            ],
            "state": self.state.get_cluster_state() if self.state else None,
        }


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
