"""TPU topology discovery.

The reference discovers compute through Ray's GCS (nodes, GPUs,
ray.cluster_resources — ref bioengine/cluster/proxy_actor.py:332-350).
Here the source of truth is JAX's device enumeration: chips, their
generation, per-chip HBM, the host (process) each chip belongs to, and
sensible default mesh shapes for a replica's sub-mesh.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ChipInfo:
    device_id: int
    platform: str              # "tpu" | "cpu" | ...
    kind: str                  # e.g. "TPU v5 lite"
    process_index: int
    hbm_bytes: Optional[int] = None
    hbm_used_bytes: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    chips: tuple[ChipInfo, ...]
    n_hosts: int
    platform: str

    @property
    def n_chips(self) -> int:
        return len(self.chips)

    @property
    def chips_per_host(self) -> int:
        return max(1, self.n_chips // max(1, self.n_hosts))

    def local_chips(self, process_index: Optional[int] = None) -> list[ChipInfo]:
        pi = (
            process_index
            if process_index is not None
            else int(os.environ.get("TPU_PROCESS_INDEX", 0))
        )
        return [c for c in self.chips if c.process_index == pi]

    def default_mesh_axes(self) -> dict[str, int]:
        """dp-major default: all chips data-parallel. Apps override via
        their manifest's mesh spec."""
        return {"dp": self.n_chips}

    def as_dict(self) -> dict:
        return {
            "platform": self.platform,
            "n_chips": self.n_chips,
            "n_hosts": self.n_hosts,
            "chips": [dataclasses.asdict(c) for c in self.chips],
        }


def detect_topology() -> TpuTopology:
    """Enumerate the visible accelerator topology via JAX."""
    import jax

    devices = jax.devices()
    chips = []
    for d in devices:
        hbm = used = None
        try:
            stats = d.memory_stats()
            if stats:
                hbm = stats.get("bytes_limit")
                used = stats.get("bytes_in_use")
        except Exception:
            pass
        chips.append(
            ChipInfo(
                device_id=d.id,
                platform=d.platform,
                kind=getattr(d, "device_kind", d.platform),
                process_index=d.process_index,
                hbm_bytes=hbm,
                hbm_used_bytes=used,
            )
        )
    n_hosts = len({c.process_index for c in chips}) or 1
    platform = chips[0].platform if chips else "none"
    return TpuTopology(chips=tuple(chips), n_hosts=n_hosts, platform=platform)
