"""Cluster-state service: resource snapshots, history, replica registry,
dead-replica logs, pending-workload queue.

Replaces the reference's detached head-node proxy actor
(ref bioengine/cluster/proxy_actor.py — per-node resources :332-350,
pending workloads :105-165, serve-replica registry :473-561, dead-replica
log retrieval :563-738) with a plain in-process service exposed over the
framework's RPC plane. The 100-entry status-history ring mirrors
ref bioengine/cluster/ray_cluster.py:844-861,171.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import psutil

from bioengine_tpu.cluster.topology import TpuTopology, detect_topology
from bioengine_tpu.utils.logger import timestamp

HISTORY_MAX = 100


@dataclass
class ReplicaRecord:
    app_id: str
    deployment: str
    replica_id: str
    registered_at: float
    device_ids: list[int] = field(default_factory=list)
    alive: bool = True
    host_id: Optional[str] = None          # None = this (controller) host
    log_tail: deque = field(default_factory=lambda: deque(maxlen=500))


@dataclass
class PendingWorkload:
    workload_id: str
    resources: dict[str, float]            # {"chips": 1, "cpus": 2, "memory_gb": 8}
    submitted_at: float


@dataclass
class HostRecord:
    """A remote worker host that joined the cluster (multi-host mode).

    The reference's analog is a SLURM-launched Ray worker node joining
    the head (ref bioengine/cluster/slurm_workers.py:153-296) whose GPUs
    become schedulable; here a ``worker_host`` process registers its
    chips and the controller leases them per replica."""

    host_id: str
    service_id: str                        # RPC service the host answers on
    topology: dict
    registered_at: float
    chips_in_use: dict[int, str] = field(default_factory=dict)
    alive: bool = True
    worker_tag: Optional[str] = None       # provisioner job tag, if any
    # host wall clock minus controller wall clock, RTT-midpoint estimate
    # measured by the host at join/rejoin — merged incident timelines
    # and telemetry attribution de-skew with it
    clock_skew_s: float = 0.0

    @property
    def n_chips(self) -> int:
        return int(self.topology.get("n_chips", 0))

    def free_chip_ids(self) -> list[int]:
        all_ids = [c["device_id"] for c in self.topology.get("chips", [])]
        return [d for d in all_ids if d not in self.chips_in_use]


class ClusterState:
    """In-memory cluster state; the worker registers its methods as an
    RPC service so dashboards/CLIs read the same shape remotely."""

    def __init__(self, topology: Optional[TpuTopology] = None):
        self._topology = topology
        self._history: deque[dict] = deque(maxlen=HISTORY_MAX)
        self._replicas: dict[str, ReplicaRecord] = {}
        self._pending: dict[str, PendingWorkload] = {}
        self._chips_in_use: dict[int, str] = {}  # device_id -> replica_id
        self.hosts: dict[str, HostRecord] = {}   # remote worker hosts
        self.started_at = time.time()

    # ---- topology / resources ----------------------------------------------

    @property
    def topology(self) -> TpuTopology:
        if self._topology is None:
            self._topology = detect_topology()
        return self._topology

    def snapshot(self) -> dict[str, Any]:
        """One resource snapshot; appended to the history ring."""
        vm = psutil.virtual_memory()
        topo = self.topology
        chips = []
        for c in topo.chips:
            chips.append(
                {
                    "device_id": c.device_id,
                    "kind": c.kind,
                    "hbm_bytes": c.hbm_bytes,
                    "in_use_by": self._chips_in_use.get(c.device_id),
                }
            )
        snap = {
            "timestamp": time.time(),
            "iso_time": timestamp(),
            "cpu_percent": psutil.cpu_percent(interval=None),
            "memory": {
                "total_bytes": vm.total,
                "available_bytes": vm.available,
            },
            "chips": chips,
            "n_chips_free": sum(
                1 for c in topo.chips if c.device_id not in self._chips_in_use
            ),
            "n_replicas": sum(1 for r in self._replicas.values() if r.alive),
            "n_pending": len(self._pending),
            "hosts": {
                h.host_id: {
                    "alive": h.alive,
                    "n_chips": h.n_chips,
                    "n_chips_free": len(h.free_chip_ids()),
                    "worker_tag": h.worker_tag,
                    "clock_skew_s": h.clock_skew_s,
                }
                for h in self.hosts.values()
            },
        }
        self._history.append(snap)
        return snap

    def get_cluster_state(self) -> dict[str, Any]:
        """The aggregate view the worker's get_status embeds."""
        snap = self._history[-1] if self._history else self.snapshot()
        return {
            "topology": self.topology.as_dict(),
            "current": snap,
            "pending_workloads": [
                {
                    "workload_id": p.workload_id,
                    "resources": p.resources,
                    # display ages against displayed wall timestamps —
                    # not SLO measurements
                    # bioengine: ignore[BE-OBS-001]
                    "age_seconds": time.time() - p.submitted_at,
                }
                for p in self._pending.values()
            ],
            # bioengine: ignore[BE-OBS-001]
            "uptime_seconds": time.time() - self.started_at,
        }

    def history(self, n: int = HISTORY_MAX) -> list[dict]:
        return list(self._history)[-n:]

    # ---- chip accounting ----------------------------------------------------

    def acquire_chips(self, replica_id: str, n: int) -> list[int]:
        free = [
            c.device_id
            for c in self.topology.chips
            if c.device_id not in self._chips_in_use
        ]
        if len(free) < n:
            raise RuntimeError(
                f"need {n} chips, only {len(free)} free "
                f"({len(self._chips_in_use)} in use)"
            )
        taken = free[:n]
        for d in taken:
            self._chips_in_use[d] = replica_id
        return taken

    def release_chips(self, replica_id: str) -> None:
        for d in [
            d for d, r in self._chips_in_use.items() if r == replica_id
        ]:
            del self._chips_in_use[d]
        if os.environ.get("BIOENGINE_FUZZ_DRILL") == "1":
            # second half of the flag-gated drill defect (see
            # mark_host_dead): host-side lease reclamation is skipped,
            # so a dead host's chips leak until the host record is
            # replaced by a rejoin
            return
        for host in self.hosts.values():
            for d in [
                d for d, r in host.chips_in_use.items() if r == replica_id
            ]:
                del host.chips_in_use[d]

    def free_chips(self) -> int:
        """Free chips on THIS host (local placement budget)."""
        return self.topology.n_chips - len(self._chips_in_use)

    def cluster_free_chips(self) -> int:
        """Free chips across the whole cluster: local + joined hosts."""
        return self.free_chips() + sum(
            len(h.free_chip_ids()) for h in self.hosts.values() if h.alive
        )

    # ---- remote hosts (multi-host placement) --------------------------------

    def register_host(
        self,
        host_id: str,
        service_id: str,
        topology: dict,
        worker_tag: Optional[str] = None,
        clock_skew_s: float = 0.0,
    ) -> None:
        self.hosts[host_id] = HostRecord(
            host_id=host_id,
            service_id=service_id,
            topology=dict(topology),
            registered_at=time.time(),
            worker_tag=worker_tag,
            clock_skew_s=float(clock_skew_s or 0.0),
        )

    def mark_host_dead(self, host_id: str) -> list[str]:
        """Drop a host; returns the replica_ids that were leased its
        chips so the controller can restart them elsewhere."""
        host = self.hosts.get(host_id)
        if host is None:
            return []
        host.alive = False
        orphans = sorted(set(host.chips_in_use.values()))
        if os.environ.get("BIOENGINE_FUZZ_DRILL") == "1":
            # Deliberate, flag-gated lease-accounting defect (the chaos
            # fuzzer's end-to-end drill): a dead host's lease table is
            # left populated, so every chip it held leaks forever. The
            # fuzzer must find this through the lease_conservation
            # universal invariant and shrink the failing schedule to a
            # minimal repro — proving the searcher + shrinker work on a
            # KNOWN bug, not just accidental ones. Never set this flag
            # outside the fuzz drill.
            return orphans
        host.chips_in_use.clear()
        return orphans

    def find_host_for_chips(self, n: int) -> Optional[HostRecord]:
        """Least-loaded-first host with >= n free chips."""
        candidates = [
            h
            for h in self.hosts.values()
            if h.alive and len(h.free_chip_ids()) >= n
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda h: len(h.chips_in_use))

    def host_acquire_chips(
        self, host_id: str, replica_id: str, n: int
    ) -> list[int]:
        host = self.hosts.get(host_id)
        if host is None or not host.alive:
            raise RuntimeError(f"host '{host_id}' is not available")
        free = host.free_chip_ids()
        if len(free) < n:
            raise RuntimeError(
                f"host '{host_id}': need {n} chips, only {len(free)} free"
            )
        taken = free[:n]
        for d in taken:
            host.chips_in_use[d] = replica_id
        return taken

    def host_adopt_chips(
        self, host_id: str, replica_id: str, device_ids: list[int]
    ) -> None:
        """Re-lease SPECIFIC chips to a replica on a REJOINING host
        (its fresh HostRecord starts with an empty lease table; the
        replica's chips are already pinned by compiled programs, so the
        lease must land on the same device ids)."""
        host = self.hosts.get(host_id)
        if host is None or not host.alive:
            raise RuntimeError(f"host '{host_id}' is not available")
        for d in device_ids:
            owner = host.chips_in_use.get(d)
            if owner not in (None, replica_id):
                raise RuntimeError(
                    f"host '{host_id}' chip {d} already leased to {owner}"
                )
        for d in device_ids:
            host.chips_in_use[d] = replica_id
        # rejoin may follow a mark_host_dead that flagged the replica's
        # record dead; it is demonstrably alive again
        rec = self._replicas.get(replica_id)
        if rec is not None:
            rec.alive = True

    # ---- pending workloads (drive the autoscaler) ---------------------------

    def add_pending(self, workload_id: str, resources: dict[str, float]) -> None:
        self._pending[workload_id] = PendingWorkload(
            workload_id, resources, time.time()
        )

    def remove_pending(self, workload_id: str) -> None:
        self._pending.pop(workload_id, None)

    def pending(self) -> list[PendingWorkload]:
        return list(self._pending.values())

    # ---- replica registry + logs -------------------------------------------

    def register_replica(
        self,
        app_id: str,
        deployment: str,
        replica_id: str,
        device_ids: Optional[list[int]] = None,
        host_id: Optional[str] = None,
    ) -> None:
        self._replicas[replica_id] = ReplicaRecord(
            app_id=app_id,
            deployment=deployment,
            replica_id=replica_id,
            registered_at=time.time(),
            device_ids=device_ids or [],
            host_id=host_id,
        )

    def mark_replica_dead(self, replica_id: str) -> None:
        rec = self._replicas.get(replica_id)
        if rec:
            rec.alive = False
        self.release_chips(replica_id)

    def append_replica_log(self, replica_id: str, line: str) -> None:
        rec = self._replicas.get(replica_id)
        if rec:
            rec.log_tail.append(line)

    def get_replica_logs(
        self, app_id: str, include_dead: bool = True, max_lines: int = 200
    ) -> dict[str, list[str]]:
        """Per-replica log tails, INCLUDING dead replicas — parity with
        the reference's dead-replica log retrieval
        (ref bioengine/cluster/proxy_actor.py:563-738)."""
        out = {}
        for rec in self._replicas.values():
            if rec.app_id != app_id:
                continue
            if not rec.alive and not include_dead:
                continue
            label = f"{rec.deployment}/{rec.replica_id}" + (
                "" if rec.alive else " (dead)"
            )
            out[label] = list(rec.log_tail)[-max_lines:]
        return out

    def replicas(self, app_id: Optional[str] = None) -> list[ReplicaRecord]:
        return [
            r
            for r in self._replicas.values()
            if app_id is None or r.app_id == app_id
        ]

    # ---- RPC surface --------------------------------------------------------

    def service_methods(self) -> dict[str, Any]:
        return {
            "get_cluster_state": lambda context=None: self.get_cluster_state(),
            "get_history": lambda n=HISTORY_MAX, context=None: self.history(n),
        }
