"""Deterministic fault injection for the request path.

Named fault points are compiled into the hot paths of the RPC client,
the RPC server, and the worker host. Each call site is guarded by the
module-level ``ACTIVE`` flag, so a production process with no faults
configured pays one global read per pass — no dict lookups, no
coroutine scheduling.

A fault is addressed by its point name and triggers on a deterministic
hit window: the ``nth`` hit (1-based) through ``nth + count - 1``.
That makes chaos tests reproducible — "drop the connection on the 3rd
replica_call" behaves identically on every run, unlike SIGKILL-based
chaos whose timing races the event loop.

Configuration is programmatic (:func:`configure`, same-process tests)
or via the ``BIOENGINE_FAULTS`` environment variable for subprocesses
(worker hosts spawned by tests)::

    BIOENGINE_FAULTS="host.replica_call=drop:3;rpc.client.send=raise:1:2"

i.e. ``;``-separated ``point=action[:nth[:count[:delay_s]]]`` entries.

Actions:

- ``raise`` — raise :class:`FaultInjected` (a ``ConnectionError``
  subclass, so the serving layer classifies it as transport).
- ``delay`` — ``await asyncio.sleep(delay_s)`` then proceed.
- ``drop`` — invoke the call site's ``drop`` callback (each site knows
  how to sever its own connection), then raise :class:`FaultInjected`.

Registered fault points:

==========================  ================================================
``rpc.client.send``         every outbound client frame (ServerConnection)
``rpc.server.send``         every outbound server frame (per websocket)
``host.replica_call``       worker host serving a routed replica call
``host.start_replica``      worker host building a shipped replica payload
==========================  ================================================
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

ACTIVE = False

_specs: dict[str, "FaultSpec"] = {}
_hits: dict[str, int] = {}


class FaultInjected(ConnectionError):
    """Raised by a triggered fault point. Subclasses ConnectionError so
    the request path treats it as a transport failure."""


@dataclass
class FaultSpec:
    point: str
    action: str                  # "raise" | "delay" | "drop"
    nth: int = 1                 # first triggering hit (1-based)
    count: int = 1 << 30         # hits that trigger, starting at nth
    delay_s: float = 0.05


def configure(
    point: str,
    action: str,
    nth: int = 1,
    count: int = 1 << 30,
    delay_s: float = 0.05,
) -> None:
    """Arm a fault point. Resets the point's hit counter."""
    global ACTIVE
    if action not in ("raise", "delay", "drop"):
        raise ValueError(f"unknown fault action '{action}'")
    _specs[point] = FaultSpec(point, action, nth, count, delay_s)
    _hits[point] = 0
    ACTIVE = True


def clear(point: Optional[str] = None) -> None:
    """Disarm one point, or everything (also zeroes hit counters)."""
    global ACTIVE
    if point is None:
        _specs.clear()
        _hits.clear()
    else:
        _specs.pop(point, None)
        _hits.pop(point, None)
    ACTIVE = bool(_specs)


def hits(point: str) -> int:
    """How many times a point has been passed since it was armed."""
    return _hits.get(point, 0)


async def hit(
    point: str,
    drop: Optional[Callable[[], Awaitable[None]]] = None,
) -> None:
    """Pass a fault point. Call sites guard with ``if faults.ACTIVE``
    so this coroutine is never even created in a clean process."""
    spec = _specs.get(point)
    if spec is None:
        return
    _hits[point] = n = _hits[point] + 1
    if not (spec.nth <= n < spec.nth + spec.count):
        return
    # a TRIGGERED fault is incident evidence: chaos tests assert the
    # flight timeline shows injected failures where they were injected
    # (guarded by ACTIVE at call sites — zero cost in clean processes)
    from bioengine_tpu.utils import flight

    flight.record(
        "fault.hit", severity="warning",
        point=point, action=spec.action, hit=n,
    )
    if spec.action == "delay":
        await asyncio.sleep(spec.delay_s)
        return
    if spec.action == "drop" and drop is not None:
        try:
            await drop()
        finally:
            raise FaultInjected(
                f"fault '{point}' dropped the connection (hit #{n})"
            )
    raise FaultInjected(f"fault '{point}' triggered (hit #{n})")


def load_env(env_value: Optional[str] = None) -> None:
    """Parse ``BIOENGINE_FAULTS`` (subprocess configuration path)."""
    raw = (
        env_value
        if env_value is not None
        else os.environ.get("BIOENGINE_FAULTS", "")
    )
    for entry in filter(None, (e.strip() for e in raw.split(";"))):
        point, _, rest = entry.partition("=")
        parts = rest.split(":")
        action = parts[0]
        nth = int(parts[1]) if len(parts) > 1 else 1
        count = int(parts[2]) if len(parts) > 2 else 1 << 30
        delay_s = float(parts[3]) if len(parts) > 3 else 0.05
        configure(point.strip(), action, nth=nth, count=count, delay_s=delay_s)


load_env()
