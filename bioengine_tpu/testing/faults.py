"""Deterministic fault injection for the request path.

Named fault points are compiled into the hot paths of the RPC client,
the RPC server, and the worker host. Each call site is guarded by the
module-level ``ACTIVE`` flag, so a production process with no faults
configured pays one global read per pass — no dict lookups, no
coroutine scheduling.

A fault is addressed by its point name and triggers on a deterministic
hit window: the ``nth`` hit (1-based) through ``nth + count - 1``.
That makes chaos tests reproducible — "drop the connection on the 3rd
replica_call" behaves identically on every run, unlike SIGKILL-based
chaos whose timing races the event loop.

Call sites that serve a NAMED party (a worker host serving replicas)
pass ``scope=`` — their own host id — so a fault can target ONE host
in the in-process multi-host harness, where every host shares this
module's state. A spec armed with a scope only triggers when the call
site's scope matches; scopeless specs trigger everywhere (the legacy
behavior). Hit counters are per-point-per-armed-spec, so a scoped
window counts only the targeted host's passes.

Configuration is programmatic (:func:`configure`, same-process tests)
or via the ``BIOENGINE_FAULTS`` environment variable for subprocesses
(worker hosts spawned by tests)::

    BIOENGINE_FAULTS="host.replica_call=drop:3;rpc.client.send=raise:1:2"
    BIOENGINE_FAULTS="host.replica_call@h1=slow_ramp:1:1000:0.2:42:20"

i.e. ``;``-separated ``point[@scope]=action[:nth[:count[:delay_s
[:seed[:ramp_hits]]]]]`` entries.

Actions:

- ``raise`` — raise :class:`FaultInjected` (a ``ConnectionError``
  subclass, so the serving layer classifies it as transport).
- ``delay`` — ``await asyncio.sleep(delay_s)`` then proceed.
- ``drop`` — invoke the call site's ``drop`` callback (each site knows
  how to sever its own connection), then raise :class:`FaultInjected`.
- ``slow_ramp`` — gray failure: ``await asyncio.sleep(d)`` where ``d``
  ramps linearly from ~0 up to ``delay_s`` over the first
  ``ramp_hits`` triggering hits, each sample scaled by a jitter factor
  drawn from the spec's OWN seeded RNG (uniform 0.5–1.5). The replica
  keeps answering — degraded, not dead — and the whole delay sequence
  replays EXACTLY for a given ``seed`` (per-point ``random.Random``,
  consumed only on triggering hits). This is what the fixed nth-hit
  ``delay`` window cannot express: a slow-but-alive replica whose
  latency excursion grows over time.

Registered fault points:

==========================  ================================================
``rpc.client.send``         every outbound client frame (ServerConnection)
``rpc.server.send``         every outbound server frame (per websocket)
``host.replica_call``       worker host serving a routed replica call
                            (scope = the serving host's id)
``host.start_replica``      worker host building a shipped replica payload
                            (scope = the building host's id)
==========================  ================================================
"""

from __future__ import annotations

import asyncio
import copy
import os
import random
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

ACTIVE = False

_specs: dict[str, "FaultSpec"] = {}
_hits: dict[str, int] = {}

_ACTIONS = ("raise", "delay", "drop", "slow_ramp")


class FaultInjected(ConnectionError):
    """Raised by a triggered fault point. Subclasses ConnectionError so
    the request path treats it as a transport failure."""


class FaultSpecError(ValueError):
    """A malformed fault spec (``BIOENGINE_FAULTS`` entry or
    :func:`configure` arguments). Raised at parse/arm time so a typo'd
    chaos configuration fails the run loudly instead of silently arming
    nothing."""


@dataclass
class FaultSpec:
    point: str
    action: str                  # "raise" | "delay" | "drop" | "slow_ramp"
    nth: int = 1                 # first triggering hit (1-based)
    count: int = 1 << 30         # hits that trigger, starting at nth
    delay_s: float = 0.05
    scope: Optional[str] = None  # only trigger when the site's scope matches
    seed: int = 0                # slow_ramp: RNG seed (deterministic replay)
    ramp_hits: int = 16          # slow_ramp: hits to reach full delay_s
    _rng: Optional[random.Random] = field(default=None, repr=False)

    def rng(self) -> random.Random:
        if self._rng is None:
            # seeded per armed spec, consumed only on triggering hits —
            # the delay sequence is a pure function of (seed, hit index)
            self._rng = random.Random(self.seed)
        return self._rng

    def ramp_delay(self, trigger_index: int) -> float:
        """Delay for the ``trigger_index``-th (1-based) TRIGGERING hit:
        linear ramp to ``delay_s`` over ``ramp_hits`` hits, jittered by
        the spec's own RNG so the shape is noisy but replayable."""
        ramp = min(1.0, trigger_index / max(1, self.ramp_hits))
        return self.delay_s * ramp * self.rng().uniform(0.5, 1.5)


def _key(point: str, scope: Optional[str]) -> str:
    return point if scope is None else f"{point}@{scope}"


def configure(
    point: str,
    action: str,
    nth: int = 1,
    count: int = 1 << 30,
    delay_s: float = 0.05,
    scope: Optional[str] = None,
    seed: int = 0,
    ramp_hits: int = 16,
) -> None:
    """Arm a fault point. Resets the point's hit counter. ``point`` may
    carry an inline ``@scope`` suffix (the env-var syntax)."""
    global ACTIVE
    if action not in _ACTIONS:
        raise FaultSpecError(
            f"unknown fault action '{action}' "
            f"(known: {', '.join(_ACTIONS)})"
        )
    if scope is None and "@" in point:
        point, _, scope = point.partition("@")
    if not point:
        raise FaultSpecError("fault spec has an empty point name")
    if nth < 1 or count < 1:
        raise FaultSpecError(
            f"fault '{point}': nth and count are 1-based positives "
            f"(got nth={nth}, count={count})"
        )
    key = _key(point, scope)
    _specs[key] = FaultSpec(
        point, action, nth, count, delay_s,
        scope=scope, seed=seed, ramp_hits=ramp_hits,
    )
    _hits[key] = 0
    ACTIVE = True


def clear(point: Optional[str] = None) -> None:
    """Disarm faults (also zeroes hit counters). ``None`` clears
    everything; a scoped name (``p@h1``) clears exactly that scope's
    spec; a bare name clears the point across every scope — so a
    scenario can heal ONE host while another's fault stays armed."""
    global ACTIVE
    if point is None:
        _specs.clear()
        _hits.clear()
    elif "@" in point:
        _specs.pop(point, None)
        _hits.pop(point, None)
    else:
        for key in [
            k for k in _specs if k.partition("@")[0] == point
        ]:
            _specs.pop(key, None)
            _hits.pop(key, None)
    ACTIVE = bool(_specs)


def clear_all() -> int:
    """Disarm EVERY fault point and zero every hit counter; returns how
    many specs were armed. The fuzz loop calls this between iterations
    so one schedule's leftover armed points (or half-consumed hit
    windows) can never bleed into the next run."""
    global ACTIVE
    n = len(_specs)
    _specs.clear()
    _hits.clear()
    ACTIVE = False
    return n


def snapshot() -> dict:
    """Capture the whole fault-layer state — armed specs (including
    each slow_ramp spec's consumed RNG state), hit counters, and the
    ACTIVE flag — so a nested harness (the fuzzer, a test) can run with
    its own faults and :func:`restore` the ambient state afterwards."""
    return {
        "specs": copy.deepcopy(_specs),
        "hits": dict(_hits),
        "active": ACTIVE,
    }


def restore(snap: dict) -> None:
    """Restore a :func:`snapshot` exactly. The module dicts are mutated
    in place (never rebound) so call sites holding references keep
    seeing the live state."""
    global ACTIVE
    _specs.clear()
    _specs.update(copy.deepcopy(snap["specs"]))
    _hits.clear()
    _hits.update(snap["hits"])
    ACTIVE = bool(snap["active"])


def hits(point: str, scope: Optional[str] = None) -> int:
    """How many times a point (optionally one scope's armed window) has
    been passed since it was armed. A bare point name sums the
    scopeless spec plus every scoped one."""
    if scope is not None or "@" in point:
        return _hits.get(_key(point, scope), 0)
    return sum(
        n for k, n in _hits.items() if k.partition("@")[0] == point
    )


def _matching_specs(point: str, scope: Optional[str]) -> list[FaultSpec]:
    out = []
    spec = _specs.get(point)
    if spec is not None:
        out.append(spec)
    if scope is not None:
        scoped = _specs.get(f"{point}@{scope}")
        if scoped is not None:
            out.append(scoped)
    return out


async def hit(
    point: str,
    drop: Optional[Callable[[], Awaitable[None]]] = None,
    scope: Optional[str] = None,
) -> None:
    """Pass a fault point. Call sites guard with ``if faults.ACTIVE``
    so this coroutine is never even created in a clean process.
    ``scope`` identifies WHOSE pass this is (e.g. the serving host's
    id) so scoped specs can target one party."""
    # EVERY matching spec counts this pass BEFORE any action fires: a
    # scopeless raise must not skip the scoped spec's counter for the
    # same pass, or the scoped window would shift depending on what
    # else happens to be armed (replay alignment breaks)
    triggered = []
    for spec in _matching_specs(point, scope):
        key = _key(spec.point, spec.scope)
        _hits[key] = n = _hits[key] + 1
        if spec.nth <= n < spec.nth + spec.count:
            triggered.append((spec, n))
    for spec, n in triggered:
        # a TRIGGERED fault is incident evidence: chaos tests assert the
        # flight timeline shows injected failures where they were
        # injected (guarded by ACTIVE at call sites — zero cost in
        # clean processes)
        from bioengine_tpu.utils import flight

        extra = {}
        if spec.action == "slow_ramp":
            extra["delay_s"] = round(spec.ramp_delay(n - spec.nth + 1), 6)
        flight.record(
            "fault.hit", severity="warning",
            point=spec.point, action=spec.action, hit=n,
            **({"scope": spec.scope} if spec.scope else {}),
            **extra,
        )
        if spec.action == "delay":
            await asyncio.sleep(spec.delay_s)
            continue
        if spec.action == "slow_ramp":
            await asyncio.sleep(extra["delay_s"])
            continue
        if spec.action == "drop" and drop is not None:
            try:
                await drop()
            finally:
                raise FaultInjected(
                    f"fault '{spec.point}' dropped the connection (hit #{n})"
                )
        raise FaultInjected(f"fault '{spec.point}' triggered (hit #{n})")


def load_env(env_value: Optional[str] = None) -> None:
    """Parse ``BIOENGINE_FAULTS`` (subprocess configuration path).
    Malformed entries raise :class:`FaultSpecError` naming the entry —
    a chaos run with a typo'd spec must fail at parse time, not run
    clean with nothing armed."""
    raw = (
        env_value
        if env_value is not None
        else os.environ.get("BIOENGINE_FAULTS", "")
    )
    for entry in filter(None, (e.strip() for e in raw.split(";"))):
        point, eq, rest = entry.partition("=")
        if not eq or not point.strip():
            raise FaultSpecError(
                f"malformed fault spec '{entry}': expected "
                "'point[@scope]=action[:nth[:count[:delay_s"
                "[:seed[:ramp_hits]]]]]'"
            )
        parts = rest.split(":")
        if len(parts) > 6:
            raise FaultSpecError(
                f"malformed fault spec '{entry}': too many ':' fields "
                f"({len(parts)}, max 6)"
            )
        action = parts[0]
        try:
            nth = int(parts[1]) if len(parts) > 1 else 1
            count = int(parts[2]) if len(parts) > 2 else 1 << 30
            delay_s = float(parts[3]) if len(parts) > 3 else 0.05
            seed = int(parts[4]) if len(parts) > 4 else 0
            ramp_hits = int(parts[5]) if len(parts) > 5 else 16
        except ValueError as e:
            raise FaultSpecError(
                f"malformed fault spec '{entry}': {e}"
            ) from None
        configure(
            point.strip(), action, nth=nth, count=count, delay_s=delay_s,
            seed=seed, ramp_hits=ramp_hits,
        )


load_env()
