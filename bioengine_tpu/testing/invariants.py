"""Universal invariant library — checked on EVERY scenario/fuzz run.

The scenario engine's per-scenario invariants answer "did this incident
hold the promises this scenario makes"; this module holds the promises
the stack makes *unconditionally*, regardless of which faults a
schedule composed. The chaos fuzzer searches the fault-schedule space
and scores every run against exactly this library, so anything added
here is automatically hunted for by ``bioengine fuzz`` — and every
hand-written scenario must keep it green (zero false positives is the
admission bar for a new universal invariant).

The library:

==========================  ================================================
``lease_conservation``      no chip lease leaks (dead hosts, dead
                            replicas) and no double-release (a live
                            replica whose lease table disagrees with
                            the host's — a freed-then-reused chip)
``no_idempotent_loss``      strict idempotent traffic never fails —
                            whatever died, failover/retry carried it
``typed_errors_only``       clients only ever see the typed error
                            taxonomy (serving/errors.py), never a raw
                            internal exception
``epoch_monotonic``         every controller restart mints a strictly
                            greater fencing epoch (journal-epoch
                            monotonicity — split-brain fencing depends
                            on it)
``table_staleness_bounded`` a router tier, if present, served from a
                            routing table younger than the bound
``settle_liveness``         post-settle: no parked futures, no open
                            scheduler groups, no in-flight batches, no
                            lingering supervised tasks
``watchdog_timeout``        the run finished inside its wall-clock
                            watchdog (a livelocked schedule fails
                            typed with a flight dump instead of
                            hanging the suite)
==========================  ================================================

Checks take a :class:`RunContext` duck-typing the scenario engine's
run state (the ``plane``, the request plan + outcomes, flight window)
and return ``(ok, detail)``; :func:`evaluate_universal` runs the whole
library. Every check must be cheap, side-effect free, and — above all
— free of false positives: a red universal invariant is treated as a
real bug by CI and by the fuzzer's shrinker.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from bioengine_tpu.utils import flight

# Exception type names a client may legitimately observe. Everything in
# serving/errors.py plus the builtin timeout it subclasses, plus the
# engine's own watchdog marker (the watchdog invariant owns that
# failure mode; typed_errors_only must not double-report it as a leak).
TYPED_CLIENT_ERRORS = frozenset(
    {
        "RetryableTransportError",
        "ReplicaUnavailableError",
        "NoHealthyReplicasError",
        "ApplicationError",
        "AdmissionRejectedError",
        "RouterSaturatedError",
        "RouterClosedError",
        "StaleEpochError",
        "StaleTableError",
        "DeadlineExceeded",
        "TimeoutError",
        "WatchdogTimeout",
    }
)


@dataclass
class RunContext:
    """Everything a universal check may look at. ``plane`` duck-types
    the scenario engine's ``_Plane`` (controller / hosts / routers /
    server / staleness_samples / epoch_history)."""

    scenario: Any
    plane: Any
    plan: list
    outcomes: list
    flight_t0: float
    scale: float = 1.0
    watchdog_fired: bool = False
    watchdog_budget_s: Optional[float] = None


# ---------------------------------------------------------------------------
# shared problem-finders (also backing the per-scenario invariant names)
# ---------------------------------------------------------------------------


def lease_problems(controller) -> list[str]:
    """Every way chip accounting can be wrong: a dead host still holding
    leases, a chip leased to a replica nobody routes, a live replica
    whose device_ids disagree with the host's lease table (the
    double-release / double-lease signature), and a controller-local
    chip leased to a dead replica."""
    state = controller.cluster_state
    problems: list[str] = []
    live_replicas = {
        r.replica_id: r
        for app in controller.apps.values()
        for reps in app.replicas.values()
        for r in reps
    }
    for host in state.hosts.values():
        if not host.alive and host.chips_in_use:
            problems.append(f"dead host {host.host_id} leaks leases")
        for chip, rid in host.chips_in_use.items():
            if rid not in live_replicas:
                problems.append(
                    f"chip {chip} on {host.host_id} leased by dead {rid}"
                )
    for chip, rid in getattr(state, "_chips_in_use", {}).items():
        if rid not in live_replicas:
            problems.append(f"local chip {chip} leased by dead {rid}")
    for rid, r in live_replicas.items():
        host_id = getattr(r, "host_id", None)
        if host_id is None or not r.device_ids:
            continue
        host = state.hosts.get(host_id)
        held = (
            [c for c, owner in host.chips_in_use.items() if owner == rid]
            if host
            else []
        )
        if host is None or sorted(held) != sorted(r.device_ids):
            problems.append(
                f"{rid} lease mismatch on {host_id}: "
                f"{held} vs {r.device_ids}"
            )
    return problems


def liveness_problems(plane) -> list[str]:
    """Post-settle leak sweep: parked RPC futures, open coalescing
    groups, in-flight scheduler batches, lingering supervised tasks."""
    from bioengine_tpu.utils import tasks as task_registry

    problems: list[str] = []
    if plane.server is not None and plane.server._pending:
        problems.append(f"server pending: {len(plane.server._pending)}")
    for host_id, host in plane.hosts.items():
        conn = host.connection
        if conn is not None and conn._pending:
            problems.append(f"{host_id} pending: {len(conn._pending)}")
    sched_owners = [("controller", plane.controller)] + [
        (r.router_id, r) for r in plane.routers
    ]
    for owner, core in sched_owners:
        for key, sched in core._schedulers.items():
            if sched.waiting or sched._open or sched._inflight:
                problems.append(
                    f"{owner} scheduler {key}: waiting={sched.waiting} "
                    f"open={len(sched._open)} inflight={len(sched._inflight)}"
                )
    lingering = [
        t for t in task_registry._BACKGROUND_TASKS if not t.done()
    ]
    if len(lingering) > 16:
        problems.append(f"{len(lingering)} lingering supervised tasks")
    return problems


# ---------------------------------------------------------------------------
# the universal checks
# ---------------------------------------------------------------------------


def check_lease_conservation(ctx: RunContext) -> tuple[bool, str]:
    problems = lease_problems(ctx.plane.controller)
    return not problems, "; ".join(problems[:6]) or "conserved"


def check_no_idempotent_loss(ctx: RunContext) -> tuple[bool, str]:
    bad = [
        (req["idx"], out)
        for req, out in zip(ctx.plan, ctx.outcomes)
        if req["stream"].strict
        and req["stream"].idempotent
        and out != "ok"
    ]
    return not bad, (
        f"{len(bad)} lost idempotent request(s): {bad[:5]}"
        if bad
        else "zero loss"
    )


def check_typed_errors_only(ctx: RunContext) -> tuple[bool, str]:
    leaks: list[tuple[int, str]] = []
    for req, out in zip(ctx.plan, ctx.outcomes):
        if not req["stream"].strict or out is None:
            continue
        if out in ("ok", "shed", "deadline", "absorbed"):
            continue
        if out == "wrong_result":
            leaks.append((req["idx"], out))
            continue
        name = out.partition(":")[2] if out.startswith("failed:") else out
        if name not in TYPED_CLIENT_ERRORS:
            leaks.append((req["idx"], out))
    return not leaks, (
        f"{len(leaks)} raw/unknown client error(s): {leaks[:5]}"
        if leaks
        else "typed taxonomy only"
    )


def check_epoch_monotonic(ctx: RunContext) -> tuple[bool, str]:
    history = [
        e for e in getattr(ctx.plane, "epoch_history", []) if e is not None
    ]
    if len(history) < 2:
        return True, f"epochs {history or '[]'} (no restart)"
    violations = [
        (a, b) for a, b in zip(history, history[1:]) if b <= a
    ]
    return not violations, (
        f"non-monotonic epoch transition(s) {violations} in {history}"
        if violations
        else f"strictly increasing: {history}"
    )


def check_table_staleness(ctx: RunContext) -> tuple[bool, str]:
    samples = getattr(ctx.plane, "staleness_samples", [])
    if not ctx.plane.routers or not samples:
        return True, "no router tier"
    bound = (
        ctx.scenario.router_staleness_bound_s or 5.0
    ) * ctx.scale
    worst = max(samples)
    return worst <= bound, (
        f"max table age {1000 * worst:.0f}ms vs bound "
        f"{1000 * bound:.0f}ms over {len(samples)} samples"
    )


def check_settle_liveness(ctx: RunContext) -> tuple[bool, str]:
    problems = liveness_problems(ctx.plane)
    return not problems, "; ".join(problems[:6]) or "drained"


def check_watchdog(ctx: RunContext) -> tuple[bool, str]:
    if ctx.watchdog_fired:
        return False, (
            f"run exceeded its {ctx.watchdog_budget_s:.1f}s wall-clock "
            "watchdog (livelock?) — flight dump 'watchdog_timeout' "
            "holds the timeline"
        )
    return True, (
        f"finished inside the {ctx.watchdog_budget_s:.1f}s watchdog"
        if ctx.watchdog_budget_s
        else "finished"
    )


UNIVERSAL_INVARIANTS: dict[str, Callable[[RunContext], tuple[bool, str]]] = {
    "lease_conservation": check_lease_conservation,
    "no_idempotent_loss": check_no_idempotent_loss,
    "typed_errors_only": check_typed_errors_only,
    "epoch_monotonic": check_epoch_monotonic,
    "table_staleness_bounded": check_table_staleness,
    "settle_liveness": check_settle_liveness,
    "watchdog_timeout": check_watchdog,
}


def evaluate_universal(ctx: RunContext) -> dict[str, tuple[bool, str]]:
    """Run the whole library; a check that itself crashes is reported
    red with the exception (an invariant that cannot evaluate is not
    silently green). Records a flight event per red verdict so merged
    incident timelines show *which* promise broke, when."""
    out: dict[str, tuple[bool, str]] = {}
    for name, check in UNIVERSAL_INVARIANTS.items():
        try:
            ok, detail = check(ctx)
        except Exception as e:  # noqa: BLE001 — a crashing check is a red check
            ok, detail = False, f"invariant check crashed: {e!r}"
        if not ok:
            flight.record(
                "invariant.red", severity="error",
                invariant=name, detail=detail[:300],
            )
        out[name] = (bool(ok), detail)
    return out


__all__ = [
    "RunContext",
    "TYPED_CLIENT_ERRORS",
    "UNIVERSAL_INVARIANTS",
    "evaluate_universal",
    "lease_problems",
    "liveness_problems",
]
