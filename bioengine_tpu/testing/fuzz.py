"""Coverage-guided chaos fuzzer — search the fault-schedule space.

Every incident the stack has survived so far was a *hand-written*
fault schedule: someone imagined the failure mode, scripted it, and
pinned it as a scenario. This module automates the imagination. A
seeded generator composes schedules from the full fault vocabulary
(host kill/rejoin, router kill, controller SIGKILL/restart, gray-
failure slow ramps, connection blips, clock skew, traffic bursts,
named fault points) onto the scenario engine's deterministic
substrate; every run is checked against the universal invariant
library (testing/invariants.py); runs that reach *novel* coverage —
a new combination of flight-event types, invariant verdicts, and
outcome classes — are kept and mutated AFL-style (drop, add, retime,
retarget, splice), boring ones are discarded.

When a schedule breaks a universal invariant, a delta-debugging
shrinker (ddmin + a local-minimality sweep) reduces it to a schedule
where removing ANY single remaining event makes the failure disappear,
then serializes it as a replayable JSON artifact. ``bioengine fuzz
--replay <file>`` re-executes an artifact bit-deterministically (the
scenario engine's one-seed contract); failing artifacts are promoted
into ``tests/fuzz_corpus/`` and replayed by tier-1 forever after.

Determinism boundaries, stated honestly: a single *schedule* replays
exactly (request plan, fault windows, and slow-ramp jitter are pure
functions of the seed — the engine's existing double-run gate), and
the generator/mutator/shrinker are pure functions of the fuzz seed.
The *search* as a whole is wall-clock-budgeted, so how MANY schedules
a budget explores varies by machine; what the fuzzer finds is always
handed back as a deterministic artifact.

The end-to-end drill: ``BIOENGINE_FUZZ_DRILL=1`` arms a deliberate
lease-accounting defect (cluster/state.py — dead-host lease
reclamation skipped). CI runs the fuzzer against it and requires the
searcher to find the bug and shrink it to a minimal repro, proving
the whole loop on a KNOWN bug, not just accidental ones.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import random
import time
import zlib
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Any, Awaitable, Callable, Optional

from bioengine_tpu.testing import faults
from bioengine_tpu.testing.scenarios import (
    FaultEvent,
    Scenario,
    Stream,
    outcome_signature,
    run_scenario_async,
)
from bioengine_tpu.utils.logger import create_logger

logger = create_logger("fuzz", log_file="off")

ARTIFACT_KIND = "bioengine-fuzz-repro"
ARTIFACT_VERSION = 1
# the only env keys an artifact may carry into a replay (an artifact is
# checked-in data — it must not be able to smuggle arbitrary env)
ARTIFACT_ENV_ALLOWLIST = ("BIOENGINE_FUZZ_DRILL",)

# ticks near the end of a run are reserved for healing + settling so a
# late fault can't turn expected-drain time into a bogus red invariant
SETTLE_MARGIN_TICKS = 10


class FuzzError(RuntimeError):
    """Fuzzer-level failure (unknown topology, malformed artifact,
    broken baseline)."""


# ---------------------------------------------------------------------------
# fuzz topologies — the substrates schedules are composed onto
# ---------------------------------------------------------------------------

# Small and short on purpose: a fuzz iteration is a full plane
# start/drive/settle/teardown, so topology cost IS search throughput.
# Per-scenario invariants are left empty — the universal library is
# the contract every schedule is held to.
TOPOLOGIES: dict[str, Scenario] = {
    "small_multihost": Scenario(
        name="fuzz_small_multihost",
        description=(
            "2 worker hosts over real websockets, durable controller — "
            "the full fault vocabulary (host/controller chaos)"
        ),
        ticks=36,
        tick_s=0.012,
        health_every=3,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        max_ongoing=16,
        service_s=0.006,
        streams=(Stream(base=2, deadline_s=8.0),),
        hedge=True,
        deadline_s=8.0,
        max_attempts=8,
        durable=True,
        client_retry=True,
        slo_ms=1e9,
        invariants=(),
        watchdog_s=60.0,
    ),
    "routed_local": Scenario(
        name="fuzz_routed_local",
        description=(
            "local replicas behind a 2-router tier — router-loss and "
            "admission chaos without host-spawn cost"
        ),
        ticks=30,
        tick_s=0.01,
        health_every=4,
        n_hosts=0,
        n_replicas=4,
        max_ongoing=16,
        service_s=0.006,
        n_routers=2,
        router_sync_every=2,
        router_staleness_bound_s=2.0,
        streams=(Stream(base=3, deadline_s=6.0),),
        hedge=False,
        deadline_s=6.0,
        slo_ms=1e9,
        invariants=(),
        watchdog_s=45.0,
    ),
}

# weighted action vocabulary per topology class (host actions need
# hosts, router actions need routers, controller SIGKILL needs the
# real RPC plane a multi-host topology brings up)
_HOST_VOCAB: tuple[tuple[str, int], ...] = (
    ("kill_host", 4),
    ("respawn_host", 2),
    ("blip", 3),
    ("slow_ramp", 3),
    ("clear_faults", 1),
    ("kill_controller", 2),
    ("stale_verb", 1),
    ("traffic_burst", 2),
    ("clock_skew", 1),
)
_ROUTER_VOCAB: tuple[tuple[str, int], ...] = (
    ("kill_router", 4),
    ("traffic_burst", 3),
)


def _vocabulary(topo: Scenario) -> list[tuple[str, int]]:
    vocab: list[tuple[str, int]] = []
    if topo.n_hosts > 0:
        vocab.extend(_HOST_VOCAB)
    if topo.n_routers > 0:
        vocab.extend(_ROUTER_VOCAB)
    if not vocab:
        raise FuzzError(
            f"topology '{topo.name}' offers no fault vocabulary"
        )
    return vocab


def _hosts_of(topo: Scenario) -> list[str]:
    return [f"h{i + 1}" for i in range(topo.n_hosts)]


def _routers_of(topo: Scenario) -> list[str]:
    return [f"r{i}" for i in range(topo.n_routers)]


# ---------------------------------------------------------------------------
# schedule generation, repair, mutation
# ---------------------------------------------------------------------------


def _random_event(
    topo: Scenario, action: str, rng: random.Random
) -> FaultEvent:
    last = topo.ticks - SETTLE_MARGIN_TICKS
    tick = rng.randint(1, max(1, last))
    host: Optional[str] = None
    kwargs: dict[str, Any] = {}
    if action in ("kill_host", "respawn_host", "blip", "slow_ramp"):
        host = rng.choice(_hosts_of(topo))
    elif action == "kill_router":
        host = rng.choice(_routers_of(topo))
    if action == "slow_ramp":
        kwargs["delay_s"] = rng.choice((0.05, 0.1, 0.2))
        kwargs["ramp_hits"] = rng.randint(6, 12)
    elif action == "traffic_burst":
        kwargs["burst"] = rng.randint(4, 20)
    elif action == "clock_skew":
        kwargs["skew_s"] = round(rng.uniform(-5.0, 5.0), 3)
    return FaultEvent(at_tick=tick, action=action, host=host, **kwargs)


def repair(topology: str, events: list[FaultEvent],
           rng: random.Random) -> list[FaultEvent]:
    """Make a candidate schedule *fair*: drop events that target the
    impossible (killing a host that is already dead, the last live
    host, or every router) and pair every controller SIGKILL with a
    restart, so a red invariant always means a broken promise — never
    "the schedule removed the whole serving plane and traffic failed,
    as designed". The generator and mutator funnel through here; the
    shrinker deliberately does NOT (its red-set-superset predicate is
    the fairness guard there)."""
    topo = TOPOLOGIES[topology]
    last = topo.ticks - SETTLE_MARGIN_TICKS
    hosts = set(_hosts_of(topo))
    routers = _routers_of(topo)

    clamped = [
        replace(
            ev,
            at_tick=min(max(1, ev.at_tick), last),
            burst=min(max(0, ev.burst), 24),
            skew_s=min(max(ev.skew_s, -10.0), 10.0),
        )
        for ev in events
    ]
    clamped.sort(key=lambda ev: (ev.at_tick, ev.action, ev.host or ""))

    out: list[FaultEvent] = []
    dead_hosts: set[str] = set()
    router_kills = 0
    controller_alive = True
    kill_tick: Optional[int] = None
    fenced_cycle = False  # a kill->restart cycle completed before tick
    for ev in clamped:
        if ev.action == "kill_host":
            if ev.host not in hosts or ev.host in dead_hosts:
                continue
            if len(hosts - dead_hosts) <= 1:
                continue  # never take the last live host
            dead_hosts.add(ev.host)
        elif ev.action == "respawn_host":
            if ev.host not in dead_hosts:
                continue  # respawning a live host would mint extras
            if not controller_alive:
                continue  # nothing to rejoin while the plane is down
            dead_hosts.discard(ev.host)
        elif ev.action in ("blip", "slow_ramp"):
            if ev.host not in hosts:
                continue
        elif ev.action == "kill_controller":
            if not controller_alive or ev.at_tick > last - 4:
                continue
            controller_alive = False
            kill_tick = ev.at_tick
        elif ev.action == "restart_controller":
            if controller_alive:
                continue
            controller_alive = True
            fenced_cycle = True
        elif ev.action == "stale_verb":
            if not fenced_cycle:
                continue  # nothing stale to replay yet
        elif ev.action == "kill_router":
            if ev.host not in routers or router_kills >= len(routers) - 1:
                continue  # keep at least one router serving
            router_kills += 1
        elif ev.action == "traffic_burst":
            if ev.burst <= 0:
                continue
        elif ev.action in ("clear_faults", "clock_skew"):
            pass
        else:
            continue  # unknown action: not in this fuzzer's vocabulary
        out.append(ev)
    if not controller_alive and kill_tick is not None:
        # pair the SIGKILL with a restart a few ticks later —
        # idempotent traffic rides client_retry across the gap.
        # Appended only when the schedule lacks one, so repairing an
        # already-fair schedule is the identity (is_fair depends on it)
        out.append(
            FaultEvent(
                at_tick=min(kill_tick + rng.randint(2, 6), last),
                action="restart_controller",
            )
        )
    out.sort(key=lambda ev: (ev.at_tick, ev.action, ev.host or ""))
    return out


def is_fair(topology: str, events: list[FaultEvent]) -> bool:
    """A schedule is *fair* iff :func:`repair` would hand it back
    unchanged — no event targets the impossible and every controller
    SIGKILL has its restart. The shrinker only explores fair
    candidates: dropping the restart from a kill/restart pair trivially
    loses all remaining traffic and would mask the interesting bug
    behind "you deleted the control plane, as designed"."""
    # the RNG only feeds the append-a-restart path, and needing an
    # append already means the schedule differs from its repair
    return repair(topology, list(events), random.Random(0)) == list(events)


def generate(topology: str, rng: random.Random,
             max_events: int = 5) -> list[FaultEvent]:
    """A fresh schedule: 1..max_events weighted-random events, repaired."""
    topo = TOPOLOGIES[topology]
    vocab = _vocabulary(topo)
    actions = [a for a, _ in vocab]
    weights = [w for _, w in vocab]
    events = [
        _random_event(topo, rng.choices(actions, weights)[0], rng)
        for _ in range(rng.randint(1, max_events))
    ]
    return repair(topology, events, rng)


def mutate(
    topology: str,
    parent: list[FaultEvent],
    rng: random.Random,
    pool: Optional[list[list[FaultEvent]]] = None,
) -> list[FaultEvent]:
    """AFL-style mutation: drop / add / retime / re-target / splice a
    slice from another interesting schedule. 1-2 ops, then repair."""
    topo = TOPOLOGIES[topology]
    events = list(parent)
    for _ in range(rng.randint(1, 2)):
        op = rng.choice(("drop", "add", "retime", "retarget", "splice"))
        if op == "drop" and events:
            events.pop(rng.randrange(len(events)))
        elif op == "add" or not events:
            vocab = _vocabulary(topo)
            action = rng.choices(
                [a for a, _ in vocab], [w for _, w in vocab]
            )[0]
            events.append(_random_event(topo, action, rng))
        elif op == "retime":
            i = rng.randrange(len(events))
            shift = rng.randint(-8, 8)
            events[i] = replace(
                events[i], at_tick=events[i].at_tick + shift
            )
        elif op == "retarget":
            i = rng.randrange(len(events))
            ev = events[i]
            if ev.action == "kill_router" and topo.n_routers:
                events[i] = replace(ev, host=rng.choice(_routers_of(topo)))
            elif ev.host is not None and topo.n_hosts:
                events[i] = replace(ev, host=rng.choice(_hosts_of(topo)))
        elif op == "splice" and pool:
            donor = rng.choice(pool)
            if donor:
                lo = rng.randrange(len(donor))
                hi = rng.randint(lo, len(donor))
                events.extend(donor[lo:hi + 1])
    return repair(topology, events, rng)


# ---------------------------------------------------------------------------
# running one schedule
# ---------------------------------------------------------------------------


async def run_schedule(
    topology: str, events: list[FaultEvent], seed: int
) -> dict:
    """Execute one schedule on its topology and return the scenario
    result artifact. The ambient fault-layer state is snapshotted and
    restored so back-to-back iterations can never leak armed fault
    points or half-consumed hit windows into each other."""
    topo = TOPOLOGIES.get(topology)
    if topo is None:
        raise FuzzError(
            f"unknown fuzz topology '{topology}' "
            f"(known: {', '.join(sorted(TOPOLOGIES))})"
        )
    snap = faults.snapshot()
    faults.clear_all()
    try:
        scenario = replace(topo, fault_script=tuple(events))
        return await run_scenario_async(scenario, seed=seed, defenses=True)
    finally:
        faults.clear_all()
        faults.restore(snap)


def red_set(result: dict) -> set[str]:
    """The required invariants a run broke."""
    return {
        k
        for k, v in result["invariants"].items()
        if v["required"] and not v["ok"]
    }


def coverage_key(result: dict) -> tuple:
    """The novelty fingerprint: which flight-event types fired, how
    every invariant came out, and which outcome classes appeared.
    Latencies are deliberately excluded — wall time is the one thing a
    replay may legitimately change."""
    return (
        tuple(result.get("flight_event_types", ())),
        tuple(
            sorted((k, v["ok"]) for k, v in result["invariants"].items())
        ),
        tuple(sorted(result["counts"])),
    )


# ---------------------------------------------------------------------------
# delta-debugging shrinker
# ---------------------------------------------------------------------------


async def shrink(
    events: list[FaultEvent],
    still_fails: Callable[[list[FaultEvent]], Awaitable[bool]],
    max_runs: int = 48,
) -> tuple[list[FaultEvent], int]:
    """ddmin to a locally-minimal failing schedule: chunked removal
    passes first, then a single-event sweep until removing ANY one
    remaining event makes the failure disappear (or the candidate
    unfair — see :func:`is_fair`). ``still_fails`` is the oracle (for
    real runs: fair AND the original red set still reproduces).
    Returns (minimal schedule, oracle invocations)."""
    runs = 0
    cur = list(events)

    async def check(cand: list[FaultEvent]) -> bool:
        nonlocal runs
        runs += 1
        return await still_fails(cand)

    # chunk phase (classic ddmin over complements)
    n = 2
    while len(cur) >= 2 and runs < max_runs:
        chunk = max(1, len(cur) // n)
        reduced = False
        for i in range(0, len(cur), chunk):
            if runs >= max_runs:
                break
            cand = cur[:i] + cur[i + chunk:]
            if await check(cand):
                cur = cand
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if chunk == 1:
                break
            n = min(len(cur), n * 2)

    # local-minimality sweep: every single-event removal must pass
    i = 0
    while i < len(cur) and runs < max_runs:
        cand = cur[:i] + cur[i + 1:]
        if await check(cand):
            cur = cand
            i = 0
        else:
            i += 1
    return cur, runs


# ---------------------------------------------------------------------------
# repro artifacts
# ---------------------------------------------------------------------------


def schedule_to_json(events: list[FaultEvent]) -> list[dict]:
    return [dataclasses.asdict(ev) for ev in events]


def schedule_from_json(rows: list[dict]) -> list[FaultEvent]:
    try:
        return [FaultEvent(**row) for row in rows]
    except TypeError as e:
        raise FuzzError(f"malformed schedule row: {e}") from None


def schedule_digest(topology: str, events: list[FaultEvent],
                    seed: int) -> str:
    payload = json.dumps(
        {"topology": topology, "seed": seed,
         "events": schedule_to_json(events)},
        sort_keys=True,
    )
    return f"{zlib.crc32(payload.encode()):08x}"


def make_artifact(
    topology: str,
    seed: int,
    events: list[FaultEvent],
    result: dict,
    env: Optional[dict] = None,
    note: str = "",
) -> dict:
    return {
        "kind": ARTIFACT_KIND,
        "version": ARTIFACT_VERSION,
        "topology": topology,
        "seed": seed,
        "events": schedule_to_json(events),
        "env": {
            k: v
            for k, v in (env or {}).items()
            if k in ARTIFACT_ENV_ALLOWLIST
        },
        "expect": {
            "passed": bool(result["passed"]),
            "red": sorted(red_set(result)),
        },
        # informational: the signature when the artifact was minted.
        # The corpus gate compares replay-vs-replay (determinism), not
        # replay-vs-history — the invariant set is allowed to grow.
        "outcome_signature": outcome_signature(result),
        "note": note,
    }


def save_artifact(path: Path | str, artifact: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    return path


def load_artifact(path: Path | str) -> dict:
    path = Path(path)
    try:
        art = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise FuzzError(f"unreadable artifact {path}: {e}") from None
    if art.get("kind") != ARTIFACT_KIND:
        raise FuzzError(f"{path} is not a {ARTIFACT_KIND} artifact")
    if art.get("version") != ARTIFACT_VERSION:
        raise FuzzError(
            f"{path}: unsupported artifact version {art.get('version')}"
        )
    if art.get("topology") not in TOPOLOGIES:
        raise FuzzError(
            f"{path}: unknown topology '{art.get('topology')}'"
        )
    return art


class _env_overlay:
    """Apply allowlisted env keys for the duration of a replay/run and
    restore the previous values exactly."""

    def __init__(self, env: dict):
        self.env = {
            k: v for k, v in env.items() if k in ARTIFACT_ENV_ALLOWLIST
        }
        self._saved: dict[str, Optional[str]] = {}

    def __enter__(self):
        for k, v in self.env.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved.clear()
        return False


async def replay_artifact(
    artifact: dict | Path | str, check_determinism: bool = True
) -> dict:
    """Re-execute a repro artifact. Returns the verdict: the replay's
    red set, whether it matches the artifact's expectation, and (when
    ``check_determinism``) whether two replays produced identical
    outcome signatures."""
    art = (
        artifact
        if isinstance(artifact, dict)
        else await asyncio.to_thread(load_artifact, artifact)
    )
    events = schedule_from_json(art["events"])
    with _env_overlay(art.get("env", {})):
        r1 = await run_schedule(art["topology"], events, art["seed"])
        r2 = (
            await run_schedule(art["topology"], events, art["seed"])
            if check_determinism
            else None
        )
    sig1 = outcome_signature(r1)
    red = sorted(red_set(r1))
    expect = art.get("expect", {})
    return {
        "result": r1,
        "red": red,
        "signature": sig1,
        "matches_expect": (
            red == list(expect.get("red", []))
            and bool(r1["passed"]) == bool(expect.get("passed"))
        ),
        "deterministic": (
            None if r2 is None else sig1 == outcome_signature(r2)
        ),
    }


# ---------------------------------------------------------------------------
# the search loop
# ---------------------------------------------------------------------------


@dataclass
class FuzzStats:
    runs: int = 0
    novel: int = 0
    failures: int = 0
    shrink_runs: int = 0
    elapsed_s: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


async def fuzz(
    topology: str = "small_multihost",
    seed: int = 0,
    budget_s: float = 120.0,
    max_runs: Optional[int] = None,
    out_dir: Optional[Path | str] = None,
    drill: bool = False,
    keep_going: bool = False,
    shrink_max_runs: int = 48,
    on_progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """The coverage-guided search: generate/mutate schedules, keep the
    novel ones, shrink any failure to a minimal replayable artifact.
    Returns ``{"stats", "artifacts", "artifact_paths", "pool"}``;
    callers decide what a failure means (the drill EXPECTS one)."""
    if topology not in TOPOLOGIES:
        raise FuzzError(
            f"unknown fuzz topology '{topology}' "
            f"(known: {', '.join(sorted(TOPOLOGIES))})"
        )
    say = on_progress or (lambda msg: logger.info(msg))
    rng = random.Random(seed)
    env = {"BIOENGINE_FUZZ_DRILL": "1"} if drill else {}
    stats = FuzzStats()
    artifacts: list[dict] = []
    artifact_paths: list[str] = []
    pool: list[list[FaultEvent]] = []
    seen: set = set()
    t0 = time.monotonic()
    deadline = t0 + budget_s

    with _env_overlay(env):
        # the empty schedule is the baseline: it must be green, or the
        # substrate itself is broken and every search result is noise
        base = await run_schedule(topology, [], seed)
        stats.runs += 1
        base_red = red_set(base)
        if base_red:
            raise FuzzError(
                f"baseline (empty schedule) is red on '{topology}': "
                f"{sorted(base_red)} — fix the substrate before fuzzing"
            )
        seen.add(coverage_key(base))
        pool.append([])

        while time.monotonic() < deadline and (
            max_runs is None or stats.runs < max_runs
        ):
            if pool and rng.random() < 0.7:
                parent = pool[rng.randrange(len(pool))]
                events = mutate(topology, parent, rng, pool)
            else:
                events = generate(topology, rng)
            if not events:
                continue
            result = await run_schedule(topology, events, seed)
            stats.runs += 1
            red = red_set(result)
            if red:
                stats.failures += 1
                say(
                    f"run {stats.runs}: RED {sorted(red)} with "
                    f"{len(events)} event(s) — shrinking"
                )

                async def still_fails(cand: list[FaultEvent]) -> bool:
                    if not is_fair(topology, cand):
                        return False  # rejected without burning a run
                    r = await run_schedule(topology, cand, seed)
                    return red <= red_set(r)

                minimal, used = await shrink(
                    events, still_fails, max_runs=shrink_max_runs
                )
                stats.shrink_runs += used
                final = await run_schedule(topology, minimal, seed)
                art = make_artifact(
                    topology,
                    seed,
                    minimal,
                    final,
                    env=env,
                    note=(
                        f"found by fuzz seed={seed} after "
                        f"{stats.runs} run(s); shrunk from "
                        f"{len(events)} to {len(minimal)} event(s) "
                        f"in {used} run(s)"
                    ),
                )
                artifacts.append(art)
                say(
                    f"  minimal repro: {len(minimal)} event(s) "
                    f"{[(e.at_tick, e.action, e.host) for e in minimal]}"
                )
                if out_dir is not None:
                    digest = schedule_digest(topology, minimal, seed)
                    path = await asyncio.to_thread(
                        save_artifact,
                        Path(out_dir) / f"fuzz-{topology}-{digest}.json",
                        art,
                    )
                    artifact_paths.append(str(path))
                    say(f"  artifact: {path}")
                if not keep_going:
                    break
                continue
            key = coverage_key(result)
            if key not in seen:
                seen.add(key)
                pool.append(events)
                stats.novel += 1
                say(
                    f"run {stats.runs}: novel coverage "
                    f"(pool={len(pool)}, "
                    f"events={[(e.at_tick, e.action) for e in events]})"
                )

    stats.elapsed_s = round(time.monotonic() - t0, 3)
    return {
        "stats": stats.as_dict(),
        "artifacts": artifacts,
        "artifact_paths": artifact_paths,
        "pool": [schedule_to_json(ev) for ev in pool],
    }


__all__ = [
    "ARTIFACT_KIND",
    "ARTIFACT_VERSION",
    "FuzzError",
    "TOPOLOGIES",
    "coverage_key",
    "fuzz",
    "generate",
    "is_fair",
    "load_artifact",
    "make_artifact",
    "mutate",
    "red_set",
    "repair",
    "replay_artifact",
    "run_schedule",
    "save_artifact",
    "schedule_digest",
    "schedule_from_json",
    "schedule_to_json",
    "shrink",
]
