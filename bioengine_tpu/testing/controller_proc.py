"""A standalone controller process for real-subprocess crash testing.

``python -m bioengine_tpu.testing.controller_proc --port P
--control-dir DIR [--deploy-dir APP --app-id ID] [--recover]``

Runs an RpcServer + journaled ServeController exactly like a
production head process, printing line-oriented progress markers a
driving test (or operator) can wait on:

- ``READY epoch=<n> phase=<phase>`` — serving; hosts may join.
- ``DEPLOYED`` — the ``--deploy-dir`` app is placed (first life only;
  the process waits for at least one worker host before deploying).
- ``RECONCILED adopted=<n> replaced=<n> dropped=<n>`` — a
  ``--recover`` life finished its reconcile and is ACTIVE.

The process then serves until killed — the test SIGKILLs it
mid-traffic and starts a second life with ``--recover`` against the
same ``--control-dir`` and port. The pre-shared admin token rides
``BIOENGINE_ADMIN_TOKEN`` so hosts' stored credentials survive the
restart, exactly as a production pre-shared token would.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
from pathlib import Path


async def _run(args: argparse.Namespace) -> int:
    from bioengine_tpu.cluster.state import ClusterState
    from bioengine_tpu.cluster.topology import TpuTopology
    from bioengine_tpu.rpc.server import RpcServer
    from bioengine_tpu.serving import ServeController

    server = RpcServer(
        host="127.0.0.1", port=args.port, admin_users=["admin"]
    )
    await server.start()
    token = os.environ.get("BIOENGINE_ADMIN_TOKEN") or "controller-proc-token"
    server.issue_token("admin", is_admin=True, token_value=token)
    controller = ServeController(
        ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
        health_check_period=args.health_period,
        control_dir=args.control_dir,
    )
    if args.recover:
        await controller.recover()
    controller.attach_rpc(server, admin_users=["admin"])
    await controller.start()
    print(
        f"READY epoch={controller.epoch} phase={controller.phase}",
        flush=True,
    )
    if args.deploy_dir and not args.recover:
        from bioengine_tpu.apps.builder import AppBuilder

        while not any(
            h.alive for h in controller.cluster_state.hosts.values()
        ):
            await asyncio.sleep(0.05)
        builder = AppBuilder(
            workdir_root=Path(args.control_dir) / "builder"
        )
        built = builder.build(
            app_id=args.app_id, local_path=Path(args.deploy_dir)
        )
        await controller.deploy(args.app_id, built.specs)
        print("DEPLOYED", flush=True)
    if args.recover:
        while controller.phase == "RECOVERING":
            await asyncio.sleep(0.05)
        report = controller.reconcile_report or {}
        print(
            f"RECONCILED adopted={report.get('adopted', 0)} "
            f"replaced={report.get('replaced', 0)} "
            f"dropped={report.get('dropped', 0)}",
            flush=True,
        )
    # serve until killed (the test's SIGKILL is the whole point)
    await asyncio.Event().wait()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="journaled ServeController in its own process"
    )
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--control-dir", required=True)
    parser.add_argument("--deploy-dir", default=None)
    parser.add_argument("--app-id", default="recovery-app")
    parser.add_argument("--recover", action="store_true")
    parser.add_argument("--health-period", type=float, default=0.25)
    args = parser.parse_args(argv)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
