"""Test-only runtime helpers (deterministic fault injection)."""
