"""Deterministic scenario engine — replayable synthetic incidents.

The chaos tests (tests/test_chaos.py) prove single failure modes with
hand-written choreography. This module generalizes them into a
**seeded, deterministic workload driver** over the same in-process
multi-host harness: a scenario composes a *load shape* (constant,
diurnal wave, bursts, tenant flood, hot-key signature skew) with a
*fault script* built on :mod:`bioengine_tpu.testing.faults` (gray
failure = seeded ``slow_ramp`` at ``host.replica_call``, preemption
storm = repeated host kills + respawns, blip storm = connection drops),
runs it time-compressed (ticks of ~10-20 ms), and checks a set of
declarative **invariants** when the run settles — zero failed
idempotent requests, exact chip accounting, no stuck pending futures,
bounded queue depths, an SLO-attainment floor, tail-latency recovery.

Everything the workload does derives from ONE seed: arrivals per tick
are a pure function of the load shape, request arguments come from a
``random.Random(seed)``, fault windows live in tick space, and the
slow-ramp delay sequence replays exactly under its derived seed. The
**request outcome sequence** — the per-request outcome class, ordered
by request index — is therefore identical across runs with the same
seed, and so are the invariant verdicts; ``outcome_signature`` distills
both into one comparable string (the CI determinism gate diffs it
across a double run).

One normalization keeps that guarantee honest: a stream marked
``strict=False`` (the flood tenant in ``tenant_flood``) records
``absorbed`` for both *served* and *shed* — best-effort flood traffic's
contract is "must not break protected traffic", and whether one flood
request squeaked through before the queue filled is timing the
scenario deliberately does not pin. Strict streams record their real
outcome class, always.

Scenarios run with defenses ON (probation + hedging, the default) or
OFF (``defenses=False``) — the ``slow_replica`` scenario run both ways
is the acceptance proof for the gray-failure machinery: same seed, same
injected degradation; with defenses the tail recovers and nothing
fails, without them the ``p99_recovery`` invariant goes red.

Entry points: :func:`run_scenario` (sync, used by the CLI / bench /
CI) and :func:`run_scenario_async` (tests already inside a loop).
``BIOENGINE_SCENARIO_SCALE`` stretches every time constant for slow
machines (2.0 = twice as slow, twice as patient).
"""

from __future__ import annotations

import asyncio
import math
import os
import random
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

from bioengine_tpu.testing import faults
from bioengine_tpu.utils import flight
from bioengine_tpu.utils.logger import create_logger

logger = create_logger("scenarios", log_file="off")

# ---------------------------------------------------------------------------
# scenario vocabulary
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Stream:
    """One deterministic arrival process. ``arrivals(tick)`` is a pure
    function — no RNG — so the request plan replays exactly."""

    name: str = "main"
    tenant: Optional[str] = None
    priority: Optional[str] = None
    strict: bool = True          # False → ok and shed both record "absorbed"
    idempotent: bool = True
    kind: str = "constant"       # constant | diurnal | burst
    base: int = 2                # arrivals per tick
    amplitude: int = 0           # diurnal peak above base
    period: int = 40             # diurnal period in ticks
    burst_every: int = 0
    burst_size: int = 0
    start_tick: int = 0
    end_tick: Optional[int] = None
    skew_keys: int = 0           # >0 → hot-key argument skew (signature skew)
    deadline_s: Optional[float] = None
    # token streaming: drive ``gen_stream`` through
    # DeploymentHandle.call_stream instead of the unary ``work`` call.
    # Generation length is gen_tokens + (a % (gen_spread + 1)) — a pure
    # function of the seeded request args, so variable-length
    # co-batching replays exactly
    streaming: bool = False
    gen_tokens: int = 16
    gen_spread: int = 0

    def arrivals(self, tick: int) -> int:
        if tick < self.start_tick:
            return 0
        if self.end_tick is not None and tick >= self.end_tick:
            return 0
        n = self.base
        if self.kind == "diurnal":
            n = round(
                self.base
                + self.amplitude
                * 0.5
                * (1.0 + math.sin(2.0 * math.pi * tick / self.period))
            )
        elif (
            self.kind == "burst"
            and self.burst_every
            and tick % self.burst_every == 0
        ):
            n += self.burst_size
        return max(0, n)


@dataclass(frozen=True)
class FaultEvent:
    """One scripted incident step, pinned to a tick. This is also the
    chaos fuzzer's schedule-event vocabulary — every field must stay
    JSON-serializable (fuzz repro artifacts are ``asdict`` of these)."""

    at_tick: int
    # kill_host | respawn_host | slow_ramp | blip | clear_faults |
    # kill_controller | restart_controller | stale_verb | kill_router |
    # traffic_burst (extra seeded arrivals at this tick) |
    # clock_skew (shift every host's reported clock by skew_s)
    action: str
    host: Optional[str] = None
    delay_s: float = 0.2         # slow_ramp target delay
    ramp_hits: int = 12          # slow_ramp hits to reach full delay
    point: str = "host.replica_call"
    burst: int = 0               # traffic_burst: extra arrivals
    skew_s: float = 0.0          # clock_skew: seconds of host-clock shift


@dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    ticks: int = 80
    tick_s: float = 0.015
    health_every: int = 3        # controller.health_tick cadence, in ticks
    # topology: n_hosts > 0 → remote replicas over real websockets
    # (chips_per_replica forces remote placement); 0 → local replicas
    n_hosts: int = 0
    n_replicas: int = 2
    chips_per_replica: int = 2
    max_ongoing: int = 16
    service_s: float = 0.008     # synthetic deployment's forward time
    scheduling: Optional[dict] = None   # SchedulingConfig kwargs → scheduler path
    streams: tuple = (Stream(),)
    fault_script: tuple = ()
    hedge: bool = True           # defenses leg hedges idempotent traffic
    deadline_s: float = 15.0
    max_attempts: int = 8
    slo_ms: float = 250.0
    slo_floor: float = 0.9
    # invariants: always required / required only when defenses are on
    invariants: tuple = (
        "zero_failed_idempotent",
        "chip_accounting_exact",
        "no_stuck_futures",
        "bounded_queues",
    )
    defended_invariants: tuple = ()
    # p99_recovery phases: requests issued before first fault tick are
    # the healthy baseline; the last `recovery_tail` requests the tail
    recovery_tail: int = 60
    recovery_factor: float = 2.0
    # outlier-detector overrides for the defenses leg (time-compressed)
    outlier: dict = field(default_factory=dict)
    # durable control plane: give the controller a journal directory
    # under the scenario workdir so kill_controller/restart_controller
    # can exercise crash recovery (serving/journal.py)
    durable: bool = False
    # driver-level retry for idempotent strict traffic: a client whose
    # CONTROLLER died retries through the restarted one while its
    # deadline budget lasts — the honest model of "zero failed
    # idempotent requests" across a control-plane restart (in-replica
    # failover can't help when the router itself is gone)
    client_retry: bool = False
    # scale-out router tier: n_routers > 0 → requests route through
    # StandaloneRouters fed by the controller's routing-table publisher
    # (clients spread round-robin by request index and fail over to a
    # sibling router on RouterClosedError — the typed-retry contract)
    n_routers: int = 0
    # per-router inflight admission cap (None → unbounded); the knob
    # that makes the fleet-scale goodput capacity-bound per router
    router_max_inflight: Optional[int] = None
    router_sync_every: int = 2   # table sync cadence, in ticks
    # bounded-staleness assertion input: max observed table age (seconds,
    # sampled just BEFORE each sync — the worst age a live router served
    # from), scaled by BIOENGINE_SCENARIO_SCALE
    router_staleness_bound_s: Optional[float] = None
    # fleet dressing: register N synthetic mesh hosts in ClusterState so
    # the published routing table carries a fleet-scale host membership
    # block (replicas stay local — the routing work is what's under test)
    sim_hosts: int = 0
    # step-level decode batch cap for streaming scenarios (the
    # deployment's DecodeLoop max_active; one slot is always the
    # interactive reserve)
    decode_max_active: int = 4
    # wall-clock watchdog: a livelocked run fails typed (the
    # watchdog_timeout universal invariant goes red with a flight dump)
    # instead of hanging the suite. None derives a generous budget from
    # ticks/deadline; the fuzzer relies on this to survive pathological
    # schedules. Scaled by BIOENGINE_SCENARIO_SCALE like everything else.
    watchdog_s: Optional[float] = None


# ---------------------------------------------------------------------------
# the synthetic deployment
# ---------------------------------------------------------------------------

_MANIFEST = """\
name: Scenario App
id: scenario-app
id_emoji: "\\U0001F9EA"
description: deterministic idempotent arithmetic for scenario traffic
type: tpu-serve
version: 1.0.0
deployments:
  - scenario_dep:ScenarioDep
authorized_users: ["*"]
deployment_config:
  scenario_dep:
    num_replicas: {n_replicas}
    min_replicas: {n_replicas}
    max_replicas: {n_replicas}
    chips: {chips}
    autoscale: false
"""

_SOURCE = """\
import asyncio
import time

from bioengine_tpu.rpc import schema_method


class _ToyDecodeBackend:
    \"\"\"Deterministic pure-python decode backend for the step-level
    continuous batcher: token i of a sequence is a pure function of its
    prompt (token_i = (sum(prompt) + i) % 251), so a resumed stream
    regenerates exactly and the scenario client can verify the full
    sequence. MUST agree with scenarios._expected_tokens.\"\"\"

    step_s = {service_s}

    def __init__(self):
        self._state = {{}}

    def prefill(self, seq_id, tokens):
        base = sum(int(t) for t in tokens) % 251
        self._state[seq_id] = [base, 1]
        time.sleep(self.step_s)
        return base

    def step(self, seq_ids, tokens):
        time.sleep(self.step_s)
        out = []
        for sid in seq_ids:
            base, n = self._state[sid]
            out.append((base + n) % 251)
            self._state[sid][1] = n + 1
        return out

    def finish(self, seq_id):
        self._state.pop(seq_id, None)


class ScenarioDep:
    service_s = {service_s}
    decode_max_active = {decode_max_active}

    def __init__(self):
        self.calls = 0
        self._decode_loop = None

    @schema_method
    async def work(self, a: int, b: int, context=None):
        \"\"\"Idempotent arithmetic with a fixed service time.\"\"\"
        self.calls += 1
        await asyncio.sleep(self.service_s)
        return {{"sum": a + b}}

    async def gen_stream(
        self,
        prompt,
        max_new_tokens: int = 16,
        klass: str = "interactive",
        resume_from: int = 0,
        context=None,
    ):
        \"\"\"Streaming generation over the step-level continuous
        batcher (serving/decode.py) — one item per token.\"\"\"
        from bioengine_tpu.serving.decode import DecodeLoop

        if self._decode_loop is None:
            self._decode_loop = DecodeLoop(
                _ToyDecodeBackend(),
                name="scenario",
                max_active=self.decode_max_active,
                interactive_reserve=1,
            )
        stream = self._decode_loop.submit(
            [int(t) for t in prompt],
            int(max_new_tokens),
            klass=klass,
            resume_from=int(resume_from or 0),
        )
        async for tok in stream.tokens():
            yield {{"token": int(tok)}}

    async def close(self):
        if self._decode_loop is not None:
            await self._decode_loop.close()
"""


def _expected_tokens(prompt: list, n: int) -> list:
    """Client-side mirror of ``_ToyDecodeBackend`` in ``_SOURCE``:
    token i = (sum(prompt) + i) % 251. The streaming driver verifies
    the WHOLE sequence against this — a resumed stream that dropped,
    duplicated or reordered a token records ``wrong_result``."""
    base = sum(prompt) % 251
    return [(base + i) % 251 for i in range(n)]


class _LocalDep:
    """Local-replica variant for host-less (scheduler-path) scenarios."""

    service_s = 0.008

    async def work(self, a: int = 0, b: int = 0):
        await asyncio.sleep(type(self).service_s)
        return {"sum": a + b}


def _build_app_dir(root: Path, scenario: Scenario) -> Path:
    """Sync helper (driven via ``asyncio.to_thread``): writes the
    scenario app's manifest + source for the AppBuilder."""
    app_dir = root / "scenario-src"
    app_dir.mkdir(parents=True, exist_ok=True)
    manifest = _MANIFEST.format(
        n_replicas=scenario.n_replicas, chips=scenario.chips_per_replica
    )
    if scenario.scheduling:
        # remote scenarios opt into the global scheduler through the
        # same manifest vocabulary operators use
        lines = ["    scheduling:"]
        for k, v in scenario.scheduling.items():
            lines.append(f"      {k}: {v}")
        manifest += "\n".join(lines) + "\n"
    (app_dir / "manifest.yaml").write_text(manifest)
    (app_dir / "scenario_dep.py").write_text(
        _SOURCE.format(
            service_s=scenario.service_s,
            decode_max_active=scenario.decode_max_active,
        )
    )
    return app_dir


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def _scale() -> float:
    try:
        return max(0.1, float(os.environ.get("BIOENGINE_SCENARIO_SCALE", "1")))
    except ValueError:
        return 1.0


def _quantile(vals: list, q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(int(len(s) * q), len(s) - 1)]


async def _kill_host(host) -> None:
    """In-process SIGKILL: sever the websocket with rejoin suppressed."""
    host.rejoin = False
    if host.connection is not None:
        host.connection.auto_reconnect = False
        host.connection._closing = True
        await host.connection._abort_connection()


class _Plane:
    """The in-process serving plane a scenario drives: controller (+
    optional RpcServer and WorkerHosts), the deployed scenario app, and
    fault-script application."""

    def __init__(self, scenario: Scenario, seed: int, defenses: bool,
                 scale: float, workdir: Path):
        self.scenario = scenario
        self.seed = seed
        self.defenses = defenses
        self.scale = scale
        self.workdir = workdir
        self.server = None
        self.controller = None
        self.hosts: dict[str, Any] = {}
        self.dead_hosts: dict[str, Any] = {}
        self._token = None
        self._port: Optional[int] = None
        self._outlier = None
        # SIGKILL'd controllers, kept so stale_verb can replay a
        # lower-epoch verb from them (the split-brain probe)
        self.old_controllers: list[Any] = []
        # every controller incarnation's fencing epoch, in order — the
        # epoch_monotonic universal invariant reads this
        self.epoch_history: list[Any] = []
        # scale-out router tier (scenario.n_routers > 0)
        self.routers: list[Any] = []
        self.killed_routers: list[str] = []
        self.router_failovers = 0          # client hops to a sibling
        self.staleness_samples: list[float] = []
        self.app_id = "scenario-app"
        self.deployment = "scenario_dep"

    async def start(self) -> None:
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.cluster.topology import TpuTopology
        from bioengine_tpu.serving import (
            DeploymentSpec,
            OutlierConfig,
            SchedulingConfig,
            ServeController,
        )

        s = self.scenario
        outlier_kwargs = {
            # time-compressed defaults sized to the tick scale; a
            # scenario may override any of them
            "ratio": 2.5,
            "recovery_ratio": 1.6,
            "excursion_s": 0.25 * self.scale,
            "min_samples": 6,
            "probe_every": 6,
            "ewma_alpha": 0.35,
            **s.outlier,
        }
        outlier = OutlierConfig(enabled=self.defenses, **outlier_kwargs)
        self._outlier = outlier
        if s.n_hosts > 0:
            from bioengine_tpu.rpc.server import RpcServer

            self.server = RpcServer(host="127.0.0.1", admin_users=["admin"])
            await self.server.start()
            self._port = self.server.port
            self._token = self.server.issue_token("admin", is_admin=True)
            self.controller = self._make_controller()
            self.controller.attach_rpc(self.server, admin_users=["admin"])
            for i in range(s.n_hosts):
                await self.spawn_host(f"h{i + 1}")
            await self._deploy_remote()
        else:
            self.controller = ServeController(
                ClusterState(), health_check_period=3600,
                outlier_config=outlier,
            )
            _LocalDep.service_s = s.service_s
            scheduling = (
                SchedulingConfig(**s.scheduling)
                if s.scheduling is not None
                else None
            )
            await self.controller.deploy(
                self.app_id,
                [
                    DeploymentSpec(
                        name=self.deployment,
                        instance_factory=_LocalDep,
                        num_replicas=s.n_replicas,
                        min_replicas=s.n_replicas,
                        max_replicas=s.n_replicas,
                        max_ongoing_requests=s.max_ongoing,
                        autoscale=False,
                        scheduling=scheduling,
                    )
                ],
            )
        if s.sim_hosts > 0:
            self._register_sim_hosts()
        if s.n_routers > 0:
            self._start_routers()
        self.epoch_history.append(getattr(self.controller, "epoch", None))

    def _register_sim_hosts(self) -> None:
        """Fleet dressing: N synthetic mesh hosts in ClusterState so the
        published routing table carries a fleet-scale membership block.
        Safe because the local plane has no RPC server — the dead-host
        prune is a no-op — and the hosts lease no chips."""
        from bioengine_tpu.cluster.state import HostRecord

        now = time.time()
        for i in range(self.scenario.sim_hosts):
            hid = f"sim{i}"
            self.controller.cluster_state.hosts[hid] = HostRecord(
                host_id=hid,
                service_id=f"svc-{hid}",
                topology={"n_chips": 4, "chips": []},
                registered_at=now,
            )

    def _start_routers(self) -> None:
        """Bring up the standalone router tier against the controller's
        routing-table publisher. The resolver re-reads ``self.controller``
        per lookup so a controller restart transparently re-resolves."""
        from bioengine_tpu.serving import (
            StandaloneRouter,
            shared_object_resolver,
        )

        s = self.scenario
        resolver = shared_object_resolver(lambda: self.controller)
        for i in range(s.n_routers):
            router = StandaloneRouter(
                f"r{i}",
                resolver,
                outlier_config=self._outlier,
                max_inflight=s.router_max_inflight,
            )
            router.sync_from(self.controller)
            self.routers.append(router)

    def sync_routers(self) -> None:
        """One table-sync round. Staleness is sampled BEFORE syncing —
        the worst age each live router actually served from — feeding
        the bounded-staleness invariant. A failed sync (controller
        mid-restart) keeps the last-good table: staleness grows, routing
        continues."""
        for router in self.routers:
            if router.closed:
                continue
            self.staleness_samples.append(router.table_staleness_s)
            try:
                router.sync_from(self.controller)
            except Exception as e:  # noqa: BLE001 — stale table keeps serving
                logger.debug(
                    f"router {router.router_id} sync failed: {e}"
                )

    def kill_router(self, router_id: Optional[str]) -> None:
        for router in self.routers:
            if router.router_id == router_id:
                router.kill()
                self.killed_routers.append(router.router_id)
                logger.info(f"scenario: router {router_id} killed")
                return
        raise ValueError(f"kill_router: unknown router '{router_id}'")

    def _make_controller(self):
        from bioengine_tpu.cluster.state import ClusterState
        from bioengine_tpu.cluster.topology import TpuTopology
        from bioengine_tpu.serving import ServeController

        kwargs: dict = {}
        if self.scenario.durable:
            kwargs["control_dir"] = str(self.workdir / "control")
        return ServeController(
            ClusterState(TpuTopology(chips=(), n_hosts=1, platform="cpu")),
            health_check_period=3600,
            outlier_config=self._outlier,
            **kwargs,
        )

    async def kill_controller(self) -> None:
        """SIGKILL-equivalent control-plane teardown: the RPC server
        vanishes (every host's websocket closes — they go ORPHANED and
        start rejoin backoff) and the controller object is abandoned
        mid-state: no drains, no undeploys, no journal goodbye. The
        journal directory is all that survives."""
        if self.server is None:
            # already dead — killing a corpse is a no-op. The fuzzer's
            # shrinker runs arbitrary subsets of a schedule, so the
            # substrate must accept unpaired lifecycle verbs.
            return
        # self.controller keeps pointing at the dead object until the
        # restart lands — exactly what a client with a stale reference
        # sees; its calls fail fast (provider gone) and client_retry
        # carries them across
        self.old_controllers.append(self.controller)
        server, self.server = self.server, None
        if server is not None:
            await server.stop()
        # callers queued inside the dead controller's schedulers would
        # otherwise wait out their full deadline — in a real SIGKILL
        # their connection to the controller process dies, so emulate
        # that: fail queued work typed NOW, drain nothing
        for sched in self.controller._schedulers.values():
            sched.kill()
        # a SIGKILL'd process refuses new connections instantly — model
        # that on the abandoned object too: calls through a stale
        # reference get a typed fast refusal (RouterClosedError) instead
        # of burning their whole deadline in _pick_replica_wait on
        # replicas a dead control plane can never re-place (the chaos
        # fuzzer found exactly that: paired kill/restart still lost
        # idempotent traffic because one slow failure ate the budget)
        from bioengine_tpu.serving.router import _RouterGate

        gate = _RouterGate(router_id="controller-sigkilled")
        gate.closed = True
        self.controller._router_gate = gate
        logger.info("scenario: controller killed (SIGKILL-equivalent)")

    async def restart_controller(self) -> None:
        """A fresh controller process-equivalent on the SAME port and
        admin token: replays snapshot+journal into RECOVERING, attaches
        the router, and lets the hosts' reconnect loops bring their
        warm-replica inventory back for reconcile."""
        if self.server is not None:
            # control plane is up — nothing to restart. An unpaired
            # restart (a shrinker candidate that dropped the kill)
            # must not try to double-bind the port.
            return
        from bioengine_tpu.rpc.server import RpcServer

        server = RpcServer(
            host="127.0.0.1", port=self._port, admin_users=["admin"]
        )
        await server.start()
        # hosts reconnect with the token the OLD control plane issued —
        # the restarted one must honor it (prod: pre-shared admin token)
        server.issue_token("admin", is_admin=True, token_value=self._token)
        controller = self._make_controller()
        await controller.recover()
        controller.attach_rpc(server, admin_users=["admin"])
        self.server = server
        self.controller = controller
        self.epoch_history.append(getattr(controller, "epoch", None))
        logger.info(
            f"scenario: controller restarted (epoch {controller.epoch}, "
            f"phase {controller.phase})"
        )

    async def stale_verb(self) -> None:
        """The split-brain probe: the SIGKILL'd controller 'revives'
        and issues a lifecycle verb with its stale epoch straight at a
        host. The host must reject it typed (StaleEpochError) and
        record ``host.fenced`` — the epoch_fencing_observed invariant
        reads that evidence."""
        old = self.old_controllers[-1] if self.old_controllers else None
        host = next(iter(self.hosts.values()), None)
        if old is None or host is None or not host.replicas:
            return
        rid = next(iter(host.replicas))
        try:
            await host.drain_replica(rid, timeout_s=0.1, epoch=old.epoch)
            logger.warning(
                "scenario: stale-epoch verb was NOT fenced "
                "(epoch_fencing_observed will fail)"
            )
        except Exception as e:  # noqa: BLE001 — the rejection IS the datum
            logger.info(f"scenario: stale verb fenced: {e}")

    async def spawn_host(self, host_id: str):
        from bioengine_tpu.worker_host import WorkerHost

        host = WorkerHost(
            server_url=self.server.url,
            token=self._token,
            host_id=host_id,
            workspace_dir=self.workdir / f"ws-{host_id}",
            rejoin=True,
        )
        await host.start()
        if host.connection is not None:
            host.connection.reconnect_max_backoff_s = 0.5
        self.hosts[host_id] = host
        self.dead_hosts.pop(host_id, None)
        return host

    async def _deploy_remote(self) -> None:
        from bioengine_tpu.apps.builder import AppBuilder

        app_dir = await asyncio.to_thread(
            _build_app_dir, self.workdir, self.scenario
        )

        def _build():
            builder = AppBuilder(workdir_root=self.workdir / "apps")
            return builder.build(app_id=self.app_id, local_path=app_dir)

        built = await asyncio.to_thread(_build)
        await self.controller.deploy(self.app_id, built.specs)

    async def apply(self, ev: FaultEvent, seed: int) -> None:
        if ev.action == "kill_host":
            host = self.hosts.pop(ev.host, None)
            if host is not None:
                self.dead_hosts[ev.host] = host
                await _kill_host(host)
        elif ev.action == "respawn_host":
            if self.server is None:
                # the control plane is down — a real preempted host
                # would retry its join until a controller answers; the
                # harness just skips the rejoin (fuzz schedules may
                # land a respawn inside a controller-dead window)
                logger.info(
                    f"scenario: respawn of {ev.host} skipped "
                    "(controller down)"
                )
                return
            old = self.dead_hosts.pop(ev.host, None)
            if old is not None:
                try:
                    await old.stop()
                except Exception as e:  # noqa: BLE001 — already-severed host
                    logger.debug(f"stop of killed host {ev.host}: {e}")
            await self.spawn_host(ev.host)
        elif ev.action == "slow_ramp":
            import zlib

            faults.configure(
                ev.point,
                "slow_ramp",
                scope=ev.host,
                delay_s=ev.delay_s * self.scale,
                # derived, not shared: the ramp's jitter stream must not
                # depend on how many other points the scenario armed.
                # crc32, NOT hash() — str hashing is randomized per
                # interpreter (PYTHONHASHSEED), which would break the
                # replay-exactly contract ACROSS invocations while the
                # in-process double run still passed
                seed=seed
                ^ (zlib.crc32((ev.host or "").encode()) & 0xFFFF)
                ^ ev.at_tick,
                ramp_hits=ev.ramp_hits,
            )
        elif ev.action == "blip":
            host = self.hosts.get(ev.host)
            if host is not None and host.connection is not None:
                await host.connection._abort_connection()
        elif ev.action == "clear_faults":
            faults.clear(ev.point)
        elif ev.action == "kill_controller":
            await self.kill_controller()
        elif ev.action == "restart_controller":
            await self.restart_controller()
        elif ev.action == "stale_verb":
            await self.stale_verb()
        elif ev.action == "kill_router":
            self.kill_router(ev.host)
        elif ev.action == "traffic_burst":
            # the burst itself lives in the request PLAN (built from the
            # fault script before the run, keeping the plan a pure
            # function of the seed) — nothing to do at apply time
            pass
        elif ev.action == "clock_skew":
            # every host's clock drifts by skew_s relative to the
            # controller: shift the recorded skew estimate and the
            # registration timestamps the way a real skewed rejoin
            # would report them (timeline merge / telemetry attribution
            # must de-skew; nothing placement-critical keys off these)
            for host in self.controller.cluster_state.hosts.values():
                host.clock_skew_s += ev.skew_s
                host.registered_at -= ev.skew_s
        else:
            raise ValueError(f"unknown fault action '{ev.action}'")

    async def stop(self) -> None:
        for router in self.routers:
            if not router.closed:
                router.kill()
        for host in list(self.hosts.values()) + list(self.dead_hosts.values()):
            try:
                await host.stop()
            except Exception as e:  # noqa: BLE001 — teardown best effort
                logger.debug(f"host {host.host_id} teardown: {e}")
        if self.controller is not None:
            await self.controller.stop()
        if self.server is not None:
            await self.server.stop()
        # stopped hosts are useless references — drop them so a plane
        # held past stop() (scenario asserts) doesn't pin every host
        self.hosts.clear()
        self.dead_hosts.clear()


async def run_scenario_async(
    scenario: Scenario,
    seed: int = 0,
    defenses: bool = True,
    workdir: Optional[Path] = None,
) -> dict:
    """Run one scenario to completion and evaluate its invariants.
    Returns the result artifact (see module docstring); raises nothing
    on invariant failure — ``result["passed"]`` is the verdict."""
    import tempfile

    from bioengine_tpu.serving import RequestOptions
    from bioengine_tpu.serving.errors import (
        AdmissionRejectedError,
        DeadlineExceeded,
        RouterClosedError,
    )

    scale = _scale()
    s = scenario
    rng = random.Random(seed)
    owns_workdir = workdir is None
    if owns_workdir:
        workdir = Path(
            await asyncio.to_thread(tempfile.mkdtemp, prefix="bioengine-scn-")
        )
    flight_t0 = time.time()
    faults.clear()
    plane = _Plane(s, seed, defenses, scale, workdir)

    # ---- deterministic request plan (pure function of seed) ----------------
    # traffic_burst events inject extra arrivals; they are folded in
    # HERE, while the plan is built, so the request plan stays a pure
    # function of (seed, scenario+fault script) and replays exactly
    burst_by_tick: dict[int, int] = {}
    for ev in s.fault_script:
        if ev.action == "traffic_burst":
            burst_by_tick[ev.at_tick] = (
                burst_by_tick.get(ev.at_tick, 0) + max(0, ev.burst)
            )
    plan: list[dict] = []
    for tick in range(s.ticks):
        for stream in s.streams:
            for _ in range(stream.arrivals(tick)):
                if stream.skew_keys:
                    # hot-key skew: 80% of traffic shares one argument
                    # tuple (one batch signature — signatures hash the
                    # scalar VALUES), the rest spreads over cold keys
                    a = (
                        0
                        if rng.random() < 0.8
                        else 1 + rng.randrange(stream.skew_keys)
                    )
                    b = 1
                else:
                    a = rng.randrange(1000)
                    b = rng.randrange(1000)
                plan.append(
                    {
                        "idx": len(plan),
                        "tick": tick,
                        "stream": stream,
                        "a": a,
                        "b": b,
                    }
                )
        for _ in range(burst_by_tick.get(tick, 0)):
            plan.append(
                {
                    "idx": len(plan),
                    "tick": tick,
                    "stream": s.streams[0],
                    "a": rng.randrange(1000),
                    "b": rng.randrange(1000),
                }
            )

    outcomes: list[Optional[str]] = [None] * len(plan)
    latencies: list[Optional[float]] = [None] * len(plan)
    queue_samples: list[int] = []

    try:
        await plane.start()
        fault_by_tick: dict[int, list[FaultEvent]] = {}
        for ev in s.fault_script:
            fault_by_tick.setdefault(ev.at_tick, []).append(ev)

        def opts_for(req: dict) -> RequestOptions:
            stream = req["stream"]
            return RequestOptions(
                idempotent=stream.idempotent,
                deadline_s=(stream.deadline_s or s.deadline_s) * scale,
                max_attempts=s.max_attempts,
                backoff_base_s=0.02,
                backoff_cap_s=0.25,
                priority=stream.priority,
                tenant=stream.tenant,
                hedge=defenses and s.hedge and stream.idempotent,
            )

        async def one(req: dict) -> None:
            idx = req["idx"]
            opts = opts_for(req)
            t0 = time.monotonic()
            # client_retry scenarios re-resolve the handle per attempt:
            # after a controller restart the surviving object is the
            # PLANE, not any one controller instance — exactly a real
            # client reconnecting to the healed control-plane URL
            budget_until = t0 + (opts.deadline_s or s.deadline_s * scale)
            # router tier: clients spread round-robin by request index;
            # a RouterClosedError (typed-retryable) hops to the next
            # sibling — each request tries at most every router once
            n_routers = len(plane.routers)
            router_offset = 0
            while True:
                try:
                    if n_routers:
                        target = plane.routers[
                            (idx + router_offset) % n_routers
                        ]
                    else:
                        target = plane.controller
                    handle = target.get_handle(
                        plane.app_id, plane.deployment
                    )
                    stream = req["stream"]
                    if stream.streaming:
                        # token streaming: drain the whole generation
                        # through call_stream (mid-stream failover
                        # resumes idempotently with resume_from) and
                        # verify every token against the deterministic
                        # backend mirror
                        prompt = [req["a"] % 251, req["b"] % 251]
                        n_tokens = stream.gen_tokens + (
                            req["a"] % (stream.gen_spread + 1)
                            if stream.gen_spread
                            else 0
                        )
                        toks: list = []
                        async for item in handle.call_stream(
                            "gen_stream",
                            prompt=prompt,
                            max_new_tokens=n_tokens,
                            klass=stream.priority or "interactive",
                            options=opts,
                        ):
                            toks.append(item["token"])
                        outcomes[idx] = (
                            "ok"
                            if toks == _expected_tokens(prompt, n_tokens)
                            else "wrong_result"
                        )
                    else:
                        r = await handle.call(
                            "work", req["a"], req["b"], options=opts
                        )
                        got = r["sum"] if isinstance(r, dict) else None
                        outcomes[idx] = (
                            "ok"
                            if got == req["a"] + req["b"]
                            else "wrong_result"
                        )
                except RouterClosedError:
                    router_offset += 1
                    plane.router_failovers += 1
                    if router_offset < n_routers:
                        continue
                    if (
                        s.client_retry
                        and req["stream"].idempotent
                        and time.monotonic() < budget_until - 0.5 * scale
                    ):
                        # no sibling absorbed it (or no router tier):
                        # the refusal came from a SIGKILL'd control
                        # plane — re-resolve through whatever controller
                        # answers next, like any transport failure
                        await asyncio.sleep(0.05 * scale)
                        continue
                    outcomes[idx] = "failed:RouterClosedError"
                except AdmissionRejectedError:
                    outcomes[idx] = "shed"
                except DeadlineExceeded:
                    outcomes[idx] = "deadline"
                except Exception as e:  # noqa: BLE001 — the outcome IS the datum
                    if (
                        s.client_retry
                        and req["stream"].idempotent
                        and time.monotonic() < budget_until - 0.5 * scale
                    ):
                        # the control plane itself may be mid-restart —
                        # an idempotent request is safe to re-issue
                        # through whatever controller answers next
                        await asyncio.sleep(0.05 * scale)
                        continue
                    outcomes[idx] = f"failed:{type(e).__name__}"
                break
            latencies[idx] = time.monotonic() - t0

        by_tick: dict[int, list[dict]] = {}
        for req in plan:
            by_tick.setdefault(req["tick"], []).append(req)

        t_run = time.monotonic()
        tasks: list[asyncio.Task] = []

        async def _drive() -> None:
            for tick in range(s.ticks):
                for ev in fault_by_tick.get(tick, ()):
                    await plane.apply(ev, seed)
                for req in by_tick.get(tick, ()):
                    tasks.append(asyncio.create_task(one(req)))
                await asyncio.sleep(s.tick_s * scale)
                queue_samples.append(
                    sum(plane.controller._queue_depth.values())
                    + sum(
                        sum(r._queue_depth.values()) for r in plane.routers
                    )
                )
                if plane.routers and tick % s.router_sync_every == 0:
                    plane.sync_routers()
                if tick % s.health_every == 0:
                    await plane.controller.health_tick()
            # drain: every request finishes (deadlines bound this), then
            # the plane settles so leak checks see steady state, not
            # shutdown. The health cadence keeps running while requests
            # drain — production's background health loop doesn't stop
            # when the traffic generator does, and a request waiting in
            # _pick_replica_wait for a re-placed replica would otherwise
            # starve out its whole deadline against a rejoined host
            # nobody tops up (found by the chaos fuzzer: kill one host,
            # blip the other near the last tick)
            drained = asyncio.Event()

            async def _drain_health() -> None:
                period = s.health_every * s.tick_s * scale
                while True:
                    try:
                        await asyncio.wait_for(drained.wait(), period)
                        return
                    except asyncio.TimeoutError:
                        await plane.controller.health_tick()

            drain_health = asyncio.create_task(_drain_health())
            try:
                await asyncio.gather(*tasks)
            finally:
                drained.set()
                await drain_health
            for _ in range(3):
                await plane.controller.health_tick()
                await asyncio.sleep(0.05 * scale)
            # detached hedge probes (a probation replica is slow by
            # definition) may still be settling — give the RPC plane a
            # bounded window to drain before the leak invariants look
            settle_until = time.monotonic() + 3.0 * scale
            while time.monotonic() < settle_until:
                pending = len(plane.server._pending) if plane.server else 0
                if not pending:
                    break
                await asyncio.sleep(0.02)

        # wall-clock watchdog: a pathological schedule (livelock, a
        # drain that never drains) fails TYPED — watchdog_timeout goes
        # red with a flight dump attached — instead of hanging the
        # suite. The fuzzer depends on this to survive schedules nobody
        # would write by hand.
        watchdog_budget = (
            s.watchdog_s
            if s.watchdog_s is not None
            else s.ticks * s.tick_s + s.deadline_s + 30.0
        ) * scale
        watchdog_fired = False
        try:
            await asyncio.wait_for(_drive(), timeout=watchdog_budget)
        except asyncio.TimeoutError:
            watchdog_fired = True
            flight.dump(
                "watchdog_timeout",
                scenario=s.name,
                budget_s=round(watchdog_budget, 3),
            )
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for i, out in enumerate(outcomes):
                if out is None:
                    outcomes[i] = "failed:WatchdogTimeout"
        wall = time.monotonic() - t_run

        result = _evaluate(
            s, seed, defenses, plane, plan, outcomes, latencies,
            queue_samples, flight_t0, wall,
            watchdog_fired=watchdog_fired,
            watchdog_budget=watchdog_budget,
        )
        return result
    finally:
        faults.clear()
        await plane.stop()
        if owns_workdir:
            import shutil

            await asyncio.to_thread(shutil.rmtree, workdir, True)


def run_scenario(
    scenario: Scenario, seed: int = 0, defenses: bool = True
) -> dict:
    return asyncio.run(run_scenario_async(scenario, seed, defenses))


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


def _evaluate(
    s: Scenario,
    seed: int,
    defenses: bool,
    plane: _Plane,
    plan: list,
    outcomes: list,
    latencies: list,
    queue_samples: list,
    flight_t0: float,
    wall: float,
    watchdog_fired: bool = False,
    watchdog_budget: Optional[float] = None,
) -> dict:
    from bioengine_tpu.testing import invariants as universal
    # normalized outcome sequence: strict streams record the real
    # class; best-effort streams (flood) collapse served/shed into
    # "absorbed" (the contract they are held to — see module docstring)
    seq = []
    for req, out in zip(plan, outcomes):
        if not req["stream"].strict and out in ("ok", "shed", "deadline"):
            seq.append("absorbed")
        else:
            seq.append(out)

    probation_events = flight.get_events(
        types=("replica.probation",), since=flight_t0
    )
    hedge_events = flight.get_events(
        types=("request.hedge",), since=flight_t0
    )

    strict_lat = [
        1000.0 * lat
        for req, lat, out in zip(plan, latencies, outcomes)
        if req["stream"].strict and out == "ok" and lat is not None
    ]
    first_fault_tick = min(
        (ev.at_tick for ev in s.fault_script), default=None
    )
    base_lat = [
        1000.0 * lat
        for req, lat, out in zip(plan, latencies, outcomes)
        if first_fault_tick is not None
        and req["tick"] < first_fault_tick
        and req["stream"].strict
        and out == "ok"
        and lat is not None
    ]
    tail_lat = [
        1000.0 * lat
        for req, lat, out in list(zip(plan, latencies, outcomes))[
            -s.recovery_tail:
        ]
        if req["stream"].strict and out == "ok" and lat is not None
    ]

    checks: dict[str, Callable[[], tuple[bool, str]]] = {
        "zero_failed_idempotent": lambda: _inv_zero_failed(plan, outcomes),
        "chip_accounting_exact": lambda: _inv_chips(plane),
        "no_stuck_futures": lambda: _inv_no_stuck(plane),
        "bounded_queues": lambda: _inv_bounded_queues(
            s, plane, queue_samples
        ),
        "slo_attainment": lambda: _inv_slo(s, strict_lat),
        "p99_recovery": lambda: _inv_recovery(s, base_lat, tail_lat),
        "probation_entered": lambda: (
            any(e["attrs"].get("phase") == "enter" for e in probation_events),
            f"{len(probation_events)} probation event(s)",
        ),
        "coalescing_observed": lambda: _inv_coalescing(plane),
        "flood_shed_observed": lambda: _inv_flood_shed(plane),
        "no_duplicate_placements": lambda: _inv_no_duplicates(plane),
        "epoch_fencing_observed": lambda: _inv_fencing(flight_t0),
        "replicas_adopted": lambda: _inv_adopted(flight_t0),
        "router_failover_observed": lambda: (
            plane.router_failovers > 0,
            f"{plane.router_failovers} client hop(s) to a sibling router",
        ),
        "router_staleness_bounded": lambda: _inv_router_staleness(s, plane),
        "decode_cobatch_observed": lambda: _inv_cobatch(flight_t0),
        "stream_resume_observed": lambda: _inv_stream_resume(flight_t0),
    }

    invariants: dict[str, dict] = {}
    for name in dict.fromkeys(
        (*s.invariants, *s.defended_invariants)
    ):
        ok, detail = checks[name]()
        invariants[name] = {
            "ok": bool(ok),
            "required": name in s.invariants
            or (defenses and name in s.defended_invariants),
            "detail": detail,
        }

    # the universal library runs on EVERY scenario, always required —
    # these are the promises the stack makes regardless of which faults
    # a schedule composed (and what `bioengine fuzz` hunts violations of)
    ctx = universal.RunContext(
        scenario=s,
        plane=plane,
        plan=plan,
        outcomes=outcomes,
        flight_t0=flight_t0,
        scale=_scale(),
        watchdog_fired=watchdog_fired,
        watchdog_budget_s=watchdog_budget,
    )
    for name, (ok, detail) in universal.evaluate_universal(ctx).items():
        invariants[name] = {
            "ok": bool(ok),
            "required": True,
            "universal": True,
            "detail": detail,
        }

    counts: dict[str, int] = {}
    for out in seq:
        counts[out] = counts.get(out, 0) + 1
    routers_section = None
    if plane.routers:
        routers_section = {
            "count": len(plane.routers),
            "killed": list(plane.killed_routers),
            "client_failovers": plane.router_failovers,
            # raw (un-normalized) served count — the goodput numerator
            # the router_scaling bench reads; best-effort capacity legs
            # normalize seq to "absorbed" but goodput wants the truth
            "raw_ok": sum(1 for out in outcomes if out == "ok"),
            "staleness_max_s": (
                round(max(plane.staleness_samples), 4)
                if plane.staleness_samples
                else None
            ),
            "staleness_samples": len(plane.staleness_samples),
            "table_epoch": plane.routers[0].table_epoch,
            "per_router": [r.describe() for r in plane.routers],
        }
    return {
        "scenario": s.name,
        "seed": seed,
        "defenses": defenses,
        "requests": len(plan),
        "wall_s": round(wall, 3),
        "counts": counts,
        "outcomes": seq,
        "invariants": invariants,
        "passed": all(
            v["ok"] for v in invariants.values() if v["required"]
        ),
        "latency_ms": {
            "p50": round(_quantile(strict_lat, 0.5) or 0.0, 2),
            "p95": round(_quantile(strict_lat, 0.95) or 0.0, 2),
            "p99": round(_quantile(strict_lat, 0.99) or 0.0, 2),
        },
        "phases": {
            "baseline_p99_ms": round(_quantile(base_lat, 0.99) or 0.0, 2),
            "tail_p99_ms": round(_quantile(tail_lat, 0.99) or 0.0, 2),
        },
        "probations": sum(
            1
            for e in probation_events
            if e["attrs"].get("phase") == "enter"
        ),
        "hedges": len(hedge_events),
        "routers": routers_section,
        # the distinct flight-event types this run produced — one third
        # of the fuzzer's coverage signature (which code paths fired,
        # not just how requests ended)
        "flight_event_types": sorted(
            {e["type"] for e in flight.get_events(since=flight_t0)}
        ),
    }


def outcome_signature(result: dict) -> str:
    """The determinism fingerprint: outcome sequence + invariant
    verdicts (NOT latencies — wall time is the one thing a replay may
    legitimately change)."""
    verdicts = ",".join(
        f"{k}={int(v['ok'])}" for k, v in sorted(result["invariants"].items())
    )
    return "|".join(result["outcomes"]) + "#" + verdicts


def _inv_zero_failed(plan, outcomes) -> tuple[bool, str]:
    bad = [
        (req["idx"], out)
        for req, out in zip(plan, outcomes)
        if req["stream"].strict
        and req["stream"].idempotent
        and out != "ok"
    ]
    return not bad, f"{len(bad)} failed idempotent request(s): {bad[:5]}"


def _inv_chips(plane: _Plane) -> tuple[bool, str]:
    # delegated to the universal library (testing/invariants.py) — the
    # per-scenario name stays for scenario definitions and old artifacts
    from bioengine_tpu.testing.invariants import lease_problems

    problems = lease_problems(plane.controller)
    return not problems, "; ".join(problems) or "exact"


def _inv_no_stuck(plane: _Plane) -> tuple[bool, str]:
    from bioengine_tpu.testing.invariants import liveness_problems

    problems = liveness_problems(plane)
    return not problems, "; ".join(problems) or "drained"


def _inv_bounded_queues(
    s: Scenario, plane: _Plane, queue_samples: list
) -> tuple[bool, str]:
    bound = s.n_replicas * s.max_ongoing * 4
    peak = max(queue_samples, default=0)
    final = sum(plane.controller._queue_depth.values()) + sum(
        sum(r._queue_depth.values()) for r in plane.routers
    )
    ok = peak <= bound and final == 0
    return ok, f"peak={peak} bound={bound} final={final}"


def _inv_router_staleness(s: Scenario, plane: _Plane) -> tuple[bool, str]:
    """Every live router's table age, sampled just before each sync
    round, stays under the scenario's bound — the 'routers serve a
    bounded-staleness view' contract."""
    if not plane.staleness_samples:
        return False, "no staleness samples (router tier absent?)"
    bound = (s.router_staleness_bound_s or 1.0) * _scale()
    worst = max(plane.staleness_samples)
    return worst <= bound, (
        f"max table age {1000 * worst:.0f}ms <= bound "
        f"{1000 * bound:.0f}ms over {len(plane.staleness_samples)} samples"
    )


def _inv_slo(s: Scenario, strict_lat: list) -> tuple[bool, str]:
    if not strict_lat:
        return False, "no successful strict requests"
    met = sum(1 for v in strict_lat if v <= s.slo_ms * _scale())
    frac = met / len(strict_lat)
    return (
        frac >= s.slo_floor,
        f"{100 * frac:.1f}% <= {s.slo_ms}ms (floor {100 * s.slo_floor:.0f}%)",
    )


def _inv_recovery(
    s: Scenario, base_lat: list, tail_lat: list
) -> tuple[bool, str]:
    if not base_lat or not tail_lat:
        return False, "missing baseline or tail window"
    base = _quantile(base_lat, 0.99)
    tail = _quantile(tail_lat, 0.99)
    # floor the baseline at one service time: an empty-queue baseline
    # p99 can sit below the service sleep on a quiet run
    floor = max(base, 1000.0 * s.service_s * _scale())
    ok = tail <= s.recovery_factor * floor
    return ok, (
        f"tail_p99={tail:.1f}ms vs {s.recovery_factor}x "
        f"baseline_p99={base:.1f}ms"
    )


def _inv_no_duplicates(plane: _Plane) -> tuple[bool, str]:
    """After a controller restart + reconcile there must be exactly one
    placement per intent: no duplicate replica ids in any routing set,
    no routing set over its journaled replica target, and no host-side
    replica the (current) controller does not route — a leftover copy
    the reconcile should have dropped or adopted."""
    problems: list[str] = []
    routed: set[str] = set()
    for app in plane.controller.apps.values():
        for name, reps in app.replicas.items():
            ids = [r.replica_id for r in reps]
            routed.update(ids)
            if len(ids) != len(set(ids)):
                problems.append(f"{app.app_id}/{name}: duplicate ids {ids}")
            spec = app.specs.get(name)
            if spec is not None and len(reps) > spec.num_replicas:
                problems.append(
                    f"{app.app_id}/{name}: {len(reps)} replicas over "
                    f"intent {spec.num_replicas}"
                )
    for host_id, host in plane.hosts.items():
        for rid, r in host.replicas.items():
            base = rid
            if getattr(r, "mesh_shard", None):
                base = (r.mesh_shard or {}).get(
                    "mesh_replica_id"
                ) or rid.rsplit("-s", 1)[0]
            if base not in routed:
                problems.append(
                    f"host {host_id} still serves unrouted replica {rid}"
                )
    return not problems, "; ".join(problems) or "exactly one placement per intent"


def _inv_fencing(flight_t0: float) -> tuple[bool, str]:
    fenced = flight.get_events(types=("host.fenced",), since=flight_t0)
    return bool(fenced), f"{len(fenced)} host.fenced event(s)"


def _inv_adopted(flight_t0: float) -> tuple[bool, str]:
    recovered = flight.get_events(
        types=("controller.recovered",), since=flight_t0
    )
    adopted = max(
        (e["attrs"].get("adopted", 0) for e in recovered), default=0
    )
    return adopted > 0, (
        f"{len(recovered)} controller.recovered event(s), "
        f"max adopted={adopted}"
    )


def _inv_cobatch(flight_t0: float) -> tuple[bool, str]:
    """Step-level continuous batching actually engaged: sequences were
    admitted INTO running batches (``decode.join`` with mid_batch=True)
    instead of waiting for a batch to drain — the no-head-of-line-
    blocking evidence."""
    joins = flight.get_events(types=("decode.join",), since=flight_t0)
    mid = sum(1 for e in joins if e["attrs"].get("mid_batch"))
    return mid > 0, f"{mid}/{len(joins)} join(s) entered a running batch"


def _inv_stream_resume(flight_t0: float) -> tuple[bool, str]:
    """A mid-generation failure was healed by idempotent stream resume
    (``decode.stream_resume`` marks the seam) — the fault script's kill
    really interrupted live generations, and nothing was lost."""
    evs = flight.get_events(
        types=("decode.stream_resume",), since=flight_t0
    )
    return bool(evs), f"{len(evs)} mid-stream resume(s)"


def _inv_coalescing(plane: _Plane) -> tuple[bool, str]:
    stats = {
        k: dict(sched.stats)
        for k, sched in plane.controller._schedulers.items()
    }
    grouped = sum(
        st["dispatched_requests"] - st["dispatched_groups"]
        for st in stats.values()
    )
    return grouped > 0, f"requests coalesced beyond groups: {grouped}"


def _inv_flood_shed(plane: _Plane) -> tuple[bool, str]:
    shed = sum(
        sched.stats["rejected"]
        for sched in plane.controller._schedulers.values()
    )
    return shed > 0, f"admission rejections: {shed}"


# ---------------------------------------------------------------------------
# named scenarios
# ---------------------------------------------------------------------------

NAMED_SCENARIOS: dict[str, Scenario] = {}


def _register(s: Scenario) -> Scenario:
    NAMED_SCENARIOS[s.name] = s
    return s


# THE acceptance scenario: one host's replica gray-fails (seeded
# slow-ramp — still passing health checks) a third of the way in and
# never heals; with defenses the outlier detector puts it in probation,
# hedges rescue the in-window tail, and deployment p99 returns to
# within 2x the healthy baseline with zero failed idempotent requests.
# With defenses OFF the same seed shows the degradation (p99_recovery
# goes red) — proving the scenario detects what the machinery fixes.
SLOW_REPLICA = _register(
    Scenario(
        name="slow_replica",
        description=(
            "gray failure: seeded slow-ramp on one host's replica path; "
            "probation + hedging steer around it"
        ),
        ticks=110,
        tick_s=0.015,
        n_hosts=3,
        n_replicas=3,
        chips_per_replica=2,
        service_s=0.008,
        streams=(Stream(base=3),),
        fault_script=(
            FaultEvent(at_tick=30, action="slow_ramp", host="h1",
                       delay_s=0.25, ramp_hits=10),
        ),
        slo_ms=400.0,
        slo_floor=0.85,
        recovery_tail=80,
        defended_invariants=("probation_entered", "p99_recovery"),
    )
)

_register(
    Scenario(
        name="preemption_storm",
        description=(
            "repeated host kills + respawns under idempotent traffic "
            "(spot/preempted TPUs)"
        ),
        ticks=100,
        tick_s=0.02,
        health_every=2,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        streams=(Stream(base=2),),
        fault_script=(
            FaultEvent(at_tick=20, action="kill_host", host="h1"),
            FaultEvent(at_tick=50, action="respawn_host", host="h1"),
            FaultEvent(at_tick=75, action="kill_host", host="h2"),
        ),
        deadline_s=20.0,
        slo_ms=2000.0,
    )
)

_register(
    Scenario(
        name="diurnal_wave",
        description=(
            "sinusoidal load wave over remote replicas — capacity and "
            "queue bounds under a compressed day"
        ),
        ticks=90,
        tick_s=0.015,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        streams=(
            Stream(kind="diurnal", base=1, amplitude=6, period=30),
        ),
        slo_ms=300.0,
        slo_floor=0.9,
        invariants=(
            "zero_failed_idempotent",
            "chip_accounting_exact",
            "no_stuck_futures",
            "bounded_queues",
            "slo_attainment",
        ),
    )
)

_register(
    Scenario(
        name="blip_storm",
        description=(
            "repeated connection drops with warm rejoin — the control "
            "plane flaps, traffic never notices"
        ),
        ticks=90,
        tick_s=0.02,
        health_every=3,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        streams=(Stream(base=2),),
        fault_script=(
            FaultEvent(at_tick=20, action="blip", host="h1"),
            FaultEvent(at_tick=45, action="blip", host="h2"),
            FaultEvent(at_tick=70, action="blip", host="h1"),
        ),
        deadline_s=20.0,
        slo_ms=2000.0,
    )
)

_register(
    Scenario(
        name="hot_signature",
        description=(
            "hot-key signature skew through the global scheduler — "
            "coalescing keeps the hot signature batched"
        ),
        ticks=70,
        tick_s=0.01,
        n_hosts=0,
        n_replicas=2,
        max_ongoing=32,
        service_s=0.006,
        scheduling={"max_batch": 16, "max_wait_ms": 4.0},
        streams=(
            Stream(kind="burst", base=2, burst_every=5, burst_size=8,
                   skew_keys=4),
        ),
        hedge=False,  # scheduler path owns placement; probation steers it
        slo_ms=500.0,
        invariants=(
            "zero_failed_idempotent",
            "no_stuck_futures",
            "bounded_queues",
            "coalescing_observed",
        ),
    )
)

_register(
    Scenario(
        name="tenant_flood",
        description=(
            "one tenant floods a scheduled deployment; quotas shed the "
            "flood, the protected tenant never fails"
        ),
        ticks=80,
        tick_s=0.01,
        n_hosts=0,
        n_replicas=2,
        max_ongoing=8,
        service_s=0.01,
        scheduling={
            # queue depth stays far above what the flood can pile up
            # (tenant_quota is the shedding mechanism under test; a
            # full queue would shed the PROTECTED tenant too)
            "max_batch": 8,
            "max_wait_ms": 2.0,
            "max_queue_depth": 512,
            "tenant_quota": 6,
        },
        streams=(
            Stream(name="protected", tenant="alice", priority="interactive",
                   base=2),
            Stream(name="flood", tenant="mallory", priority="bulk",
                   strict=False, base=0, kind="burst", burst_every=2,
                   burst_size=24, start_tick=20, end_tick=60),
        ),
        hedge=False,
        slo_ms=800.0,
        invariants=(
            "zero_failed_idempotent",
            "no_stuck_futures",
            "flood_shed_observed",
        ),
    )
)


# The durable-control-plane acceptance scenario: the CONTROLLER itself
# is SIGKILL'd mid-mixed-priority traffic (the hosts go orphaned but
# keep serving warm replicas), restarted against the same journal
# directory, and must reconcile — re-adopting every surviving replica
# in place, placing nothing twice, and fencing a lower-epoch verb from
# the "revived" old controller. Client-side retry models what a real
# client does when the control-plane URL heals: idempotent requests
# re-issue, so "zero failed idempotent" spans the restart.
CONTROLLER_CRASH = _register(
    Scenario(
        name="controller_crash",
        description=(
            "SIGKILL the controller mid-traffic; journal replay + host "
            "inventory reconcile recovers with zero loss and epoch "
            "fencing rejects the old controller"
        ),
        ticks=130,
        tick_s=0.02,
        health_every=3,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        max_ongoing=16,
        service_s=0.008,
        scheduling={
            "max_batch": 8,
            "max_wait_ms": 2.0,
            "max_queue_depth": 1024,
        },
        streams=(
            Stream(name="interactive", priority="interactive", base=2),
            Stream(name="bulk", priority="bulk", base=1),
        ),
        fault_script=(
            FaultEvent(at_tick=35, action="kill_controller"),
            FaultEvent(at_tick=45, action="restart_controller"),
            FaultEvent(at_tick=95, action="stale_verb"),
        ),
        hedge=False,            # scheduled deployment — scorer owns placement
        durable=True,
        client_retry=True,
        deadline_s=30.0,
        max_attempts=8,
        slo_ms=5000.0,
        invariants=(
            "zero_failed_idempotent",
            "chip_accounting_exact",
            "no_stuck_futures",
            "bounded_queues",
            "no_duplicate_placements",
            "replicas_adopted",
            "epoch_fencing_observed",
        ),
        recovery_tail=60,
        recovery_factor=6.0,
    )
)


# The scale-out routing-tier capacity scenario (and the workload under
# the router_scaling bench): hundreds of simulated mesh hosts in the
# published table, a large local replica pool, and offered load far
# over what ONE router's inflight cap can admit. Goodput is therefore
# capacity-bound per router — adding routers adds admitted goodput
# near-linearly until the offered load is fully served. The stream is
# best-effort (strict=False): shed-at-the-router is the designed
# behavior for the over-subscribed legs, so ok/shed normalize to
# "absorbed" and the raw served count rides in result["routers"].
FLEET_SCALE = _register(
    Scenario(
        name="fleet_scale",
        description=(
            "fleet-scale routing-table fan-out: offered load beyond one "
            "router's admission capacity; goodput scales with routers"
        ),
        ticks=40,
        tick_s=0.015,
        health_every=1000,       # one pass at tick 0 — no churn to heal
        n_hosts=0,
        n_replicas=160,
        sim_hosts=320,
        max_ongoing=16,
        service_s=0.05,
        n_routers=4,
        router_max_inflight=8,
        router_sync_every=2,
        router_staleness_bound_s=1.0,
        streams=(Stream(name="fleet", strict=False, base=24,
                        deadline_s=5.0),),
        hedge=False,             # capacity probe — no duplicate attempts
        deadline_s=5.0,
        slo_ms=5000.0,
        invariants=(
            "no_stuck_futures",
            "bounded_queues",
            "router_staleness_bounded",
        ),
    )
)


# The router-loss acceptance scenario: three routers, one SIGKILL'd
# mid-traffic. In-flight requests on the dead router finish (kill only
# closes admission); new arrivals that land on it get the typed
# RouterClosedError and hop to a sibling — zero idempotent loss, and
# the surviving routers' table staleness stays bounded throughout.
ROUTER_LOSS = _register(
    Scenario(
        name="router_loss",
        description=(
            "SIGKILL one of three routers mid-traffic; clients fail "
            "over to siblings typed, zero idempotent loss"
        ),
        ticks=80,
        tick_s=0.015,
        health_every=4,
        n_hosts=0,
        n_replicas=6,
        max_ongoing=16,
        service_s=0.01,
        n_routers=3,
        router_sync_every=2,
        router_staleness_bound_s=1.0,
        streams=(Stream(base=3),),
        hedge=False,
        fault_script=(
            FaultEvent(at_tick=30, action="kill_router", host="r1"),
        ),
        slo_ms=1000.0,
        invariants=(
            "zero_failed_idempotent",
            "no_stuck_futures",
            "bounded_queues",
            "router_failover_observed",
            "router_staleness_bounded",
        ),
    )
)


# The token-streaming acceptance scenario: interactive generations
# arrive every tick while bursts of long bulk generations co-batch with
# them in the replicas' step-level decode loops — the interactive
# reserve keeps the bulk burst from occupying the whole batch, so
# variable-length co-batching never starves short streams. Mid-run one
# host is SIGKILL-equivalently severed while generations are in flight:
# idempotent streams resume on the surviving replica with
# ``resume_from`` (greedy regeneration skips the already-delivered
# prefix), the client verifies EVERY token against the deterministic
# backend mirror, and the lease/liveness universals prove nothing
# leaked. hedge=False: a generation is a stateful stream — duplicate
# attempts would double-decode, resume is the failover mechanism.
TOKEN_STREAMING = _register(
    Scenario(
        name="token_streaming",
        description=(
            "token streaming under a long-generation burst + host kill "
            "mid-generation: step-level co-batching, interactive never "
            "starved, killed streams resume idempotently"
        ),
        ticks=90,
        tick_s=0.02,
        health_every=3,
        n_hosts=2,
        n_replicas=2,
        chips_per_replica=2,
        max_ongoing=32,
        service_s=0.004,          # decode step time (see _SOURCE)
        decode_max_active=6,
        streams=(
            Stream(name="interactive", priority="interactive",
                   streaming=True, gen_tokens=6, gen_spread=4, base=1,
                   deadline_s=15.0),
            Stream(name="bulk", priority="bulk", streaming=True,
                   gen_tokens=80, base=0, kind="burst", burst_every=20,
                   burst_size=3, start_tick=20, end_tick=70,
                   deadline_s=25.0),
        ),
        fault_script=(
            FaultEvent(at_tick=45, action="kill_host", host="h1"),
        ),
        hedge=False,
        deadline_s=25.0,
        max_attempts=8,
        slo_ms=4000.0,
        slo_floor=0.85,
        invariants=(
            "zero_failed_idempotent",
            "chip_accounting_exact",
            "no_stuck_futures",
            "bounded_queues",
            "slo_attainment",
            "decode_cobatch_observed",
            "stream_resume_observed",
        ),
    )
)


def get_scenario(name: str) -> Scenario:
    try:
        return NAMED_SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario '{name}' "
            f"(known: {', '.join(sorted(NAMED_SCENARIOS))})"
        ) from None


def list_scenarios() -> list[dict]:
    return [
        {
            "name": s.name,
            "description": s.description,
            "ticks": s.ticks,
            "hosts": s.n_hosts,
            "replicas": s.n_replicas,
            "routers": s.n_routers,
            "scheduled": s.scheduling is not None,
            "faults": [
                {"tick": ev.at_tick, "action": ev.action, "host": ev.host}
                for ev in s.fault_script
            ],
            "invariants": list(s.invariants),
            "defended_invariants": list(s.defended_invariants),
        }
        for s in NAMED_SCENARIOS.values()
    ]


__all__ = [
    "FaultEvent",
    "NAMED_SCENARIOS",
    "Scenario",
    "Stream",
    "get_scenario",
    "list_scenarios",
    "outcome_signature",
    "run_scenario",
    "run_scenario_async",
]
