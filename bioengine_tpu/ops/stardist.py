"""StarDist star-convex polygon ops: training targets + reconstruction.

The upstream stardist package implements these in C/OpenCL; the
reference only consumes them through zoo model packages. Here they are
first-class numpy ops (host-side post/pre-processing around the jitted
``models.stardist.StarDist2D`` forward):

- ``masks_to_stardist`` — per-pixel (prob, ray-distance) training
  targets from an instance-label image, vectorized as ``max_dist``
  stepped gathers per ray instead of a per-pixel walk.
- ``polygons_to_masks`` — greedy prob-ordered NMS over thresholded
  candidates + polygon rasterization back to an instance-label image.
"""

from __future__ import annotations

import warnings

import numpy as np


def ray_angles(n_rays: int) -> np.ndarray:
    return (2.0 * np.pi / n_rays) * np.arange(n_rays, dtype=np.float32)


def masks_to_stardist(
    masks: np.ndarray, n_rays: int = 32, max_dist: int = 64
) -> tuple[np.ndarray, np.ndarray]:
    """Instance labels (H, W) int -> (prob (H, W), dist (H, W, n_rays)).

    prob is the per-instance edt-normalized distance transform (the
    upstream StarDist recipe): 1.0 on each instance's medial axis,
    falling to ~0 at its boundary. A model trained on this target peaks
    at object centers, so the greedy prob-ordered NMS in
    ``polygons_to_masks`` picks medial-axis pixels as polygon centers
    instead of arbitrary interior ones.
    dist[y, x, r] = steps along ray r until the label under the ray
    differs from the label at (y, x), capped at ``max_dist``.
    """
    from scipy import ndimage

    H, W = masks.shape
    yy, xx = np.mgrid[:H, :W]
    dist = np.zeros((H, W, n_rays), np.float32)
    inside = masks > 0
    prob = np.zeros((H, W), np.float32)
    # find_objects gets every bounding box in ONE image pass; the
    # per-label work below is then proportional to box area, not H*W
    for lbl, slc in enumerate(ndimage.find_objects(masks), start=1):
        if slc is None:
            continue
        box = masks[slc] == lbl
        # pad so instance pixels touching the crop edge still measure a
        # distance-to-background; edt is per-instance so touching
        # neighbours form a boundary (a global edt would merge them)
        d = ndimage.distance_transform_edt(np.pad(box, 1))[1:-1, 1:-1]
        peak = d.max()
        if peak > 0:
            prob[slc][box] = (d / peak)[box].astype(np.float32)
    for r, ang in enumerate(ray_angles(n_rays)):
        dy, dx = np.sin(ang), np.cos(ang)
        still = inside.copy()
        for t in range(1, max_dist + 1):
            fy = np.round(yy + t * dy).astype(np.int64)
            fx = np.round(xx + t * dx).astype(np.int64)
            in_image = (fy >= 0) & (fy < H) & (fx >= 0) & (fx < W)
            py = np.clip(fy, 0, H - 1)
            px = np.clip(fx, 0, W - 1)
            # leaving the image counts as leaving the instance
            same = still & in_image & (masks[py, px] == masks)
            dist[..., r][same] = t
            still = same
            if not still.any():
                break
    return prob, dist


def _render_polygon(
    canvas: np.ndarray, cy: int, cx: int, dists: np.ndarray, label: int
) -> tuple[int, int]:
    """Rasterize one star-convex polygon: a pixel belongs to the
    instance if its distance from the center is below the (angularly
    interpolated) ray distance in its direction. Paints only unclaimed
    pixels; returns (painted, blocked) pixel counts, where blocked =
    in-image polygon pixels already claimed by accepted instances."""
    H, W = canvas.shape
    n_rays = len(dists)
    rmax = int(np.ceil(dists.max()))
    y0, y1 = max(0, cy - rmax), min(H, cy + rmax + 1)
    x0, x1 = max(0, cx - rmax), min(W, cx + rmax + 1)
    if y0 >= y1 or x0 >= x1:
        return 0, 0
    yy, xx = np.mgrid[y0:y1, x0:x1]
    dy = (yy - cy).astype(np.float32)
    dx = (xx - cx).astype(np.float32)
    rad = np.sqrt(dy * dy + dx * dx)
    ang = np.arctan2(dy, dx) % (2.0 * np.pi)
    # linear interpolation between neighbouring rays
    pos = ang / (2.0 * np.pi) * n_rays
    i0 = np.floor(pos).astype(np.int64) % n_rays
    i1 = (i0 + 1) % n_rays
    w1 = (pos - np.floor(pos)).astype(np.float32)
    boundary = dists[i0] * (1.0 - w1) + dists[i1] * w1
    inside = rad <= boundary
    blocked = inside & (canvas[y0:y1, x0:x1] != 0)
    sel = inside & ~blocked
    canvas[y0:y1, x0:x1][sel] = label
    return int(sel.sum()), int(blocked.sum())


def polygons_to_masks(
    prob: np.ndarray,
    dist: np.ndarray,
    prob_threshold: float = 0.5,
    nms_iou_threshold: float = 0.4,
    min_size: int = 15,
    max_candidates: int = 10_000,
) -> np.ndarray:
    """(prob (H, W) in [0, 1], dist (H, W, n_rays)) -> instance labels.

    Greedy NMS in probability order: a candidate is accepted unless its
    center already lies inside an accepted instance or its rendered
    overlap with existing instances exceeds ``nms_iou_threshold`` of
    its own area (render-based suppression — simpler than upstream's
    polygon-IoU but equivalent for the thresholded pipeline)."""
    from bioengine_tpu.ops.flows import filter_and_relabel

    H, W = prob.shape
    cand = np.argwhere(prob > prob_threshold)
    if len(cand) == 0:
        return np.zeros((H, W), np.int32)
    if len(cand) > max_candidates:
        # subsample SPATIALLY (per-cell argmax on a stride grid sized to
        # the budget) rather than by a global prob cutoff: a global
        # top-k drops every candidate of any cell whose peak prob falls
        # below the k-th pixel, silently losing whole instances on
        # large dense images. A grid keeps one (locally best) candidate
        # per neighbourhood everywhere — the same idea as upstream
        # StarDist's ``grid`` candidate subsampling.
        n_orig = len(cand)
        p = prob[cand[:, 0], cand[:, 1]]
        stride = max(2, int(np.ceil(np.sqrt(n_orig / max_candidates))))
        n_cols = (W + stride - 1) // stride
        cell = (cand[:, 0] // stride) * n_cols + cand[:, 1] // stride
        by_cell = np.lexsort((-p, cell))
        # within-cell rank by prob: every cell's best candidate outranks
        # ANY cell's second-best, so truncating to the budget keeps one
        # locally-max candidate per neighbourhood everywhere before
        # spending budget on runners-up — no instance loses its peak
        # unless there are more occupied cells than budget
        c_sorted = cell[by_cell]
        is_first = np.ones(n_orig, bool)
        is_first[1:] = c_sorted[1:] != c_sorted[:-1]
        first = np.maximum.accumulate(
            np.where(is_first, np.arange(n_orig), 0)
        )
        rank = np.arange(n_orig) - first
        final = np.lexsort((-p[by_cell], rank))[:max_candidates]
        cand = cand[by_cell[final]]
        warnings.warn(
            f"polygons_to_masks: {n_orig} candidates exceeded "
            f"max_candidates={max_candidates}; grid-subsampled "
            f"(stride {stride}) to {len(cand)}",
            stacklevel=2,
        )
    order = np.argsort(-prob[cand[:, 0], cand[:, 1]], kind="stable")
    cand = cand[order]
    canvas = np.zeros((H, W), np.int32)
    label = 0
    for cy, cx in cand:
        if canvas[cy, cx] != 0:
            continue  # center already claimed: suppressed
        dists = dist[cy, cx]
        if dists.max() < 1.0:
            continue
        label += 1
        painted, blocked = _render_polygon(
            canvas, int(cy), int(cx), dists, label
        )
        # actual overlap with accepted instances, measured against the
        # IN-IMAGE polygon footprint — image-border clipping must not
        # count as overlap or edge cells get systematically suppressed
        covered = blocked / max(painted + blocked, 1)
        if painted == 0 or covered > nms_iou_threshold:
            canvas[canvas == label] = 0
            label -= 1
    return filter_and_relabel(canvas, min_size)


def predictions_to_masks_stardist(
    pred: np.ndarray,
    prob_threshold: float = 0.5,
    nms_iou_threshold: float = 0.4,
    min_size: int = 15,
) -> np.ndarray:
    """Network output (H, W, 1 + n_rays) -> instance labels. Channel 0
    is the probability LOGIT (models.stardist.StarDist2D)."""
    prob = 1.0 / (1.0 + np.exp(-pred[..., 0]))
    return polygons_to_masks(
        prob,
        pred[..., 1:],
        prob_threshold=prob_threshold,
        nms_iou_threshold=nms_iou_threshold,
        min_size=min_size,
    )
