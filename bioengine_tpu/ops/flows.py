"""Flow-field ops for cellpose-style segmentation.

The reference delegates all of this to the cellpose package's CUDA/torch
implementation (ref apps/cellpose-finetuning/main.py:1278-1360 calls into
cellpose's train loop; mask reconstruction happens inside cellpose).
Here the ops are first-class:

- ``masks_to_flows``  — host-side (numpy/scipy) training-target generation:
  per-instance heat diffusion from the cell center, flows = normalized
  gradient of the heat map.
- ``follow_flows`` / ``follow_flows_3d`` — device-side (JAX) Euler
  integration of pixel/voxel positions through the predicted flow field
  via ``lax.scan`` — static iteration count, bi-/trilinear gather, runs
  fused on TPU right after the network forward pass.
- ``masks_from_flows`` — host-side clustering of converged sinks into
  instance labels; dimension-agnostic (2D images and 3D volumes).
- ``aggregate_orthogonal_flows`` — the cellpose ``do_3D`` recipe:
  2D-network outputs over yx/zx/zy slice orientations -> one 3D flow
  field.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from scipy import ndimage

# Training targets scale unit-norm flows by this factor (see
# bioengine_tpu.models.cellpose.cellpose_loss); raw network flow output
# must be divided by it before Euler integration.
FLOW_SCALE = 5.0


def masks_to_flows(masks: np.ndarray, n_iter: int | None = None) -> np.ndarray:
    """Compute (2, H, W) target flows from an instance-label image.

    For each instance, diffuse heat from the instance's median pixel and
    take the normalized gradient — the cellpose training-target recipe.
    """
    H, W = masks.shape
    flows = np.zeros((2, H, W), np.float32)
    for lbl in np.unique(masks):
        if lbl == 0:
            continue
        ys, xs = np.nonzero(masks == lbl)
        y0, y1 = ys.min(), ys.max() + 1
        x0, x1 = xs.min(), xs.max() + 1
        # pad the crop by 1 so diffusion has a zero boundary
        crop = (masks[y0:y1, x0:x1] == lbl)
        h = np.zeros((crop.shape[0] + 2, crop.shape[1] + 2), np.float64)
        cy = int(np.median(ys)) - y0 + 1
        cx = int(np.median(xs)) - x0 + 1
        inside = np.pad(crop, 1)
        iters = n_iter or 2 * max(crop.shape)
        for _ in range(iters):
            h[cy, cx] += 1.0
            h_new = 0.25 * (
                h[:-2, 1:-1] + h[2:, 1:-1] + h[1:-1, :-2] + h[1:-1, 2:]
            )
            h[1:-1, 1:-1] = np.where(inside[1:-1, 1:-1], h_new, 0.0)
        hlog = np.log1p(h[1:-1, 1:-1])
        gy, gx = np.gradient(hlog)
        norm = np.sqrt(gy**2 + gx**2) + 1e-10
        flows[0, y0:y1, x0:x1][crop] = (gy / norm)[crop]
        flows[1, y0:y1, x0:x1][crop] = (gx / norm)[crop]
    return flows


def _bilinear_sample(field: jax.Array, p: jax.Array) -> jax.Array:
    """Sample (H, W) ``field`` at float positions p=(2, N) with clamping."""
    H, W = field.shape
    y = jnp.clip(p[0], 0.0, H - 1.0)
    x = jnp.clip(p[1], 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = y - y0
    wx = x - x0
    v00 = field[y0, x0]
    v01 = field[y0, x1]
    v10 = field[y1, x0]
    v11 = field[y1, x1]
    return (
        v00 * (1 - wy) * (1 - wx)
        + v01 * (1 - wy) * wx
        + v10 * wy * (1 - wx)
        + v11 * wy * wx
    )


def follow_flows(
    flow: jax.Array, n_iter: int = 200, step: float = 1.0
) -> jax.Array:
    """Integrate every pixel through the flow field on device.

    flow: (2, H, W) predicted flows (dy, dx). Returns final positions
    (2, H, W). Pure + jittable: ``lax.scan`` with a static trip count.
    """
    H, W = flow.shape[1:]
    yy, xx = jnp.meshgrid(
        jnp.arange(H, dtype=jnp.float32),
        jnp.arange(W, dtype=jnp.float32),
        indexing="ij",
    )
    p0 = jnp.stack([yy.ravel(), xx.ravel()])  # (2, H*W)

    def body(p, _):
        dy = _bilinear_sample(flow[0], p)
        dx = _bilinear_sample(flow[1], p)
        p = jnp.stack(
            [
                jnp.clip(p[0] + step * dy, 0.0, H - 1.0),
                jnp.clip(p[1] + step * dx, 0.0, W - 1.0),
            ]
        )
        return p, None

    p_final, _ = jax.lax.scan(body, p0, None, length=n_iter)
    return p_final.reshape(2, H, W)


def _trilinear_sample(field: jax.Array, p: jax.Array) -> jax.Array:
    """Sample (D, H, W) ``field`` at float positions p=(3, N), clamped."""
    D, H, W = field.shape
    z = jnp.clip(p[0], 0.0, D - 1.0)
    y = jnp.clip(p[1], 0.0, H - 1.0)
    x = jnp.clip(p[2], 0.0, W - 1.0)
    z0 = jnp.floor(z).astype(jnp.int32)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    z1 = jnp.minimum(z0 + 1, D - 1)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wz, wy, wx = z - z0, y - y0, x - x0
    out = 0.0
    for zi, wzi in ((z0, 1 - wz), (z1, wz)):
        for yi, wyi in ((y0, 1 - wy), (y1, wy)):
            for xi, wxi in ((x0, 1 - wx), (x1, wx)):
                out = out + field[zi, yi, xi] * wzi * wyi * wxi
    return out


def follow_flows_3d(
    flow: jax.Array, n_iter: int = 200, step: float = 1.0
) -> jax.Array:
    """Integrate every voxel through a (3, D, H, W) flow field (dz, dy,
    dx) on device. Returns final positions (3, D, H, W). Same
    ``lax.scan`` structure as the 2D ``follow_flows``."""
    D, H, W = flow.shape[1:]
    zz, yy, xx = jnp.meshgrid(
        jnp.arange(D, dtype=jnp.float32),
        jnp.arange(H, dtype=jnp.float32),
        jnp.arange(W, dtype=jnp.float32),
        indexing="ij",
    )
    p0 = jnp.stack([zz.ravel(), yy.ravel(), xx.ravel()])  # (3, D*H*W)
    limits = jnp.array([[D - 1.0], [H - 1.0], [W - 1.0]], jnp.float32)

    def body(p, _):
        dp = jnp.stack([_trilinear_sample(flow[i], p) for i in range(3)])
        p = jnp.clip(p + step * dp, 0.0, limits)
        return p, None

    p_final, _ = jax.lax.scan(body, p0, None, length=n_iter)
    return p_final.reshape(3, D, H, W)


def aggregate_orthogonal_flows(
    pred_yx: np.ndarray, pred_zx: np.ndarray, pred_zy: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Combine per-orientation 2D network outputs over a (D, H, W)
    volume into a 3D flow field — the cellpose ``do_3D`` recipe (the
    upstream library runs its 2D net on yx/zx/zy slices and averages
    the shared flow components; the reference delegates to it).

    pred_yx: (D, H, W, 3) — z-slices:  channels (dy, dx, cellprob)
    pred_zx: (H, D, W, 3) — y-slices:  channels (dz, dx, cellprob)
    pred_zy: (W, D, H, 3) — x-slices:  channels (dz, dy, cellprob)

    Returns (flow (3, D, H, W) in (dz, dy, dx) order, cellprob (D, H, W));
    each flow component is the mean of its two contributing orientations,
    cellprob the mean of all three.
    """
    yx = np.asarray(pred_yx, np.float32)                     # [z, y, x, c]
    zx = np.transpose(np.asarray(pred_zx, np.float32), (1, 0, 2, 3))  # [z, y, x, c]
    zy = np.transpose(np.asarray(pred_zy, np.float32), (1, 2, 0, 3))  # [z, y, x, c]
    if not (yx.shape == zx.shape == zy.shape):
        raise ValueError(
            f"orientation outputs disagree after realignment: "
            f"{yx.shape} vs {zx.shape} vs {zy.shape}"
        )
    flow = np.stack(
        [
            (zx[..., 0] + zy[..., 0]) / 2.0,   # dz
            (yx[..., 0] + zy[..., 1]) / 2.0,   # dy
            (yx[..., 1] + zx[..., 1]) / 2.0,   # dx
        ]
    )
    cellprob = (yx[..., 2] + zx[..., 2] + zy[..., 2]) / 3.0
    return flow, cellprob


def predictions_to_masks(
    pred: np.ndarray,
    cellprob_threshold: float = 0.0,
    min_size: int = 15,
    n_iter: int = 200,
) -> np.ndarray:
    """Network output (H, W, 3) -> instance masks.

    The training target scales unit-norm flows by 5x (see
    ``bioengine_tpu.models.cellpose.cellpose_loss``), so predictions are
    rescaled by 1/5 here before flow-following — without this, Euler
    steps overshoot ~5 px and sinks scatter instead of converging.
    """
    flow = np.moveaxis(pred[..., :2], -1, 0) / FLOW_SCALE
    return masks_from_flows(
        flow,
        pred[..., 2],
        cellprob_threshold=cellprob_threshold,
        min_size=min_size,
        n_iter=n_iter,
    )


def masks_from_flows(
    flow: np.ndarray,
    cellprob: np.ndarray,
    cellprob_threshold: float = 0.0,
    min_size: int = 15,
    n_iter: int = 200,
) -> np.ndarray:
    """Postprocess *unit-scale* flows + cellprob logits -> instance labels.

    flow (2, H, W) + cellprob (H, W) for planar data, or (3, D, H, W) +
    (D, H, W) for volumes — the sink-cluster recipe (scipy ndimage) is
    dimension-agnostic. For raw network output use
    ``predictions_to_masks`` (handles the 5x training-target scale)."""
    fg = cellprob > cellprob_threshold
    if not fg.any():
        return np.zeros_like(cellprob, dtype=np.int32)
    follow = follow_flows if flow.shape[0] == 2 else follow_flows_3d
    p = np.asarray(follow(jnp.asarray(flow), n_iter=n_iter))
    spatial = cellprob.shape
    sinks = np.zeros(spatial, bool)
    idx = tuple(
        np.clip(np.round(p[d][fg]).astype(int), 0, spatial[d] - 1)
        for d in range(len(spatial))
    )
    sinks[idx] = True
    # Dilate sinks so nearby convergence points merge into one seed blob.
    seed_labels, _ = ndimage.label(ndimage.binary_dilation(sinks, iterations=2))
    masks = np.zeros(spatial, np.int32)
    masks[fg] = seed_labels[idx]
    return filter_and_relabel(masks, min_size)


def filter_and_relabel(masks: np.ndarray, min_size: int) -> np.ndarray:
    """Drop instances smaller than ``min_size`` pixels/voxels and
    re-label the rest densely 1..N. Re-run after any resampling of a
    label image: resampling can erase instances, leaving id gaps that
    make ``masks.max()`` lie about the cell count."""
    labels, counts = np.unique(masks[masks > 0], return_counts=True)
    small = set(labels[counts < min_size].tolist())
    if small:
        masks = np.where(np.isin(masks, list(small)), 0, masks)
    out = np.zeros_like(masks)
    for i, lbl in enumerate(np.unique(masks[masks > 0]), start=1):
        out[masks == lbl] = i
    return out
