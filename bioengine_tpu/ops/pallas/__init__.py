"""Pallas TPU kernels for the compute hot path.

Kernels run compiled via Mosaic on TPU and fall back to interpreter
mode on the CPU backend so the hermetic test suite exercises them
without hardware.
"""

from bioengine_tpu.ops.pallas.attention import flash_attention, make_attn_fn

__all__ = ["flash_attention", "make_attn_fn"]
