"""Flash attention as a Pallas TPU kernel.

The hot op of the ViT embedder (cell-image-search) and any future
sequence model. The reference runs torch scaled-dot-product attention
through CUDA (ref apps/cell-image-search/embedder.py:40-70); here the
whole softmax(QK^T)V is one fused Mosaic kernel: K/V blocks stream
through VMEM while an online-softmax accumulator (running max m,
normalizer l, weighted sum acc) lives in f32 scratch — attention
probabilities never round-trip to HBM, so the op is bounded by the MXU,
not HBM bandwidth.

Layout: grid = (batch*heads, num_q_blocks, num_kv_blocks); the kv axis
is innermost so scratch carries across kv steps for one q block.
Accumulators init at kv==0 and the normalized output is written at the
last kv step. Sequence padding (to the block size) and the causal
option are handled with ``broadcasted_iota`` masks; fully-masked
causal blocks skip their matmuls via ``pl.when``.

On non-TPU backends (hermetic CPU tests) the kernel runs in
interpreter mode automatically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _attn_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_scratch,
    l_scratch,
    acc_scratch,
    *,
    scale: float,
    seq_len: int,
    block_q: int,
    block_k: int,
    causal: bool,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_k

    # Row/col token ids of this tile, for padding + causal masks.
    row_ids = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    col_ids = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def compute():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)  # (block_k, d)

        s = jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)

        mask = col_ids < seq_len
        if causal:
            mask = jnp.logical_and(mask, col_ids <= row_ids)
        s = jnp.where(mask, s, NEG_INF)

        # m/l scratch are (block_q, 128) with the value broadcast across
        # lanes (keeps buffers tile-aligned); column 0 is authoritative.
        m_prev = m_scratch[:, :1]  # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scratch[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scratch[:] * alpha + jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        acc_scratch[:] = acc

    if causal:
        # Dynamic skip: whole tile above the diagonal → no contribution.
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == last_k)
    def _finish():
        l = l_scratch[:, :1]
        # Fully-padded q rows have l == 0; emit zeros, not NaN.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[:] / safe_l).astype(o_ref.dtype)


def _pad_to(x, size, axis):
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _reference_attention(q, k, v, causal):
    """Plain-XLA attention — the custom-VJP backward recomputes through
    this (flash forward + XLA backward: correct grads everywhere; a
    fused Pallas backward kernel is a later optimization)."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhnd,bhmd->bhnm", qf * scale, kf)
    if causal:
        n = q.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        s = jnp.where((col <= row)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhnm,bhmd->bhnd", p, vf).astype(q.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret), (
        q,
        k,
        v,
    )


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _reference_attention(q, k, v, causal), q, k, v
    )
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention. q, k, v: (B, H, N, d) → (B, H, N, d).

    Self-attention shapes only (same N for q and kv). N and d are
    padded to tile boundaries internally (d to a multiple of 128 —
    lane width; zero-padded d contributes nothing to QK^T and the
    extra output columns are sliced off). Differentiable via custom
    VJP (XLA-recompute backward).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    B, H, N, d = q.shape
    scale = d**-0.5

    import math

    n_pad = math.lcm(block_q, block_k)
    N_p = ((N + n_pad - 1) // n_pad) * n_pad
    d_p = ((d + 127) // 128) * 128

    qp = _pad_to(_pad_to(q, N_p, 2), d_p, 3).reshape(B * H, N_p, d_p)
    kp = _pad_to(_pad_to(k, N_p, 2), d_p, 3).reshape(B * H, N_p, d_p)
    vp = _pad_to(_pad_to(v, N_p, 2), d_p, 3).reshape(B * H, N_p, d_p)

    grid = (B * H, N_p // block_q, N_p // block_k)

    kernel = functools.partial(
        _attn_kernel,
        scale=scale,
        seq_len=N,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, block_q, d_p),
                lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d_p),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d_p),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d_p),
            lambda b, i, j: (b, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, N_p, d_p), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d_p), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * N_p * N_p * d_p,
            bytes_accessed=(3 * B * H * N_p * d_p + B * H * N_p * d_p)
            * q.dtype.itemsize,
            transcendentals=B * H * N_p * N_p,
        ),
        interpret=interpret,
    )(qp, kp, vp)

    return out.reshape(B, H, N_p, d_p)[:, :, :N, :d]


def make_attn_fn(**kwargs):
    """Adapter for ``models.vit.Attention(attn_fn=...)``: (q,k,v)→out."""

    def attn_fn(q, k, v):
        return flash_attention(q, k, v, **kwargs)

    return attn_fn
