"""Exact k-nearest-neighbour search on TPU.

The reference delegates similarity search to FAISS on CPU
(ref apps/cell-image-search/index_manager.py:36-183; published numbers:
<5 ms FlatIP at 100K vectors, <80 ms IVFPQ at 58M). On TPU, exact
inner-product search is a tall matmul — the MXU's best case — so the
flat path needs no quantization up to HBM capacity (bf16 corpus:
~10M x 768 vectors per chip), and shards across a mesh axis for more:
each device scores its corpus shard and a tiny (k-sized) all-gather
merges the per-shard top-k.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bioengine_tpu.parallel.mesh import get_shard_map


@functools.partial(jax.jit, static_argnames=("k",))
def topk_inner_product(
    corpus: jax.Array, queries: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Exact top-k by inner product. corpus (N, d), queries (Q, d) →
    (scores (Q, k), indices (Q, k)). Matmul in the corpus dtype
    (bf16 doubles on-chip capacity), scores accumulated in f32."""
    scores = jax.lax.dot_general(
        queries.astype(corpus.dtype),
        corpus,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Q, N)
    return jax.lax.top_k(scores, k)


class ShardedKnnIndex:
    """Flat inner-product index with the corpus sharded over a mesh axis.

    Per-device partial top-k then a host-side merge of k*n_shards
    candidates — the collective payload is O(Q*k), not O(N).
    """

    def __init__(
        self,
        corpus: np.ndarray,
        mesh: Optional[Mesh] = None,
        axis: str = "dp",
        dtype=jnp.bfloat16,
    ):
        self.n, self.d = corpus.shape
        self.mesh = mesh
        self.axis = axis
        if mesh is not None:
            n_shards = mesh.shape[axis]
            pad = (-self.n) % n_shards
            self._pad = pad
            padded = np.pad(corpus, ((0, pad), (0, 0)))
            sharding = NamedSharding(mesh, P(axis, None))
            self.corpus = jax.device_put(
                jnp.asarray(padded, dtype), sharding
            )
        else:
            self._pad = 0
            self.corpus = jnp.asarray(corpus, dtype)

    def search(
        self, queries: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """→ (scores (Q, k), indices (Q, k)) as numpy, global ids."""
        k = min(k, self.n)
        q = jnp.asarray(queries, jnp.float32)
        if q.ndim == 1:
            q = q[None]
        if self.mesh is None:
            s, i = topk_inner_product(self.corpus, q, k)
            return np.asarray(s), np.asarray(i)

        n_shards = self.mesh.shape[self.axis]
        shard_n = self.corpus.shape[0] // n_shards
        k_local = min(k, shard_n)

        shard_map = get_shard_map()

        @functools.partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P()),
            out_specs=(P(self.axis), P(self.axis)),
        )
        def _search(corpus_blk, q_blk):
            s, i = topk_inner_product(corpus_blk, q_blk, k_local)
            return s[None], i[None]  # leading shard axis

        s, i = _search(self.corpus, q)  # (n_shards, Q, k)
        s, i = np.asarray(s), np.asarray(i)
        # globalize ids and merge the n_shards * k candidates per query
        offsets = (np.arange(n_shards) * shard_n)[:, None, None]
        i = i + offsets
        s = np.moveaxis(s, 0, 1).reshape(q.shape[0], -1)  # (Q, n_shards*k)
        i = np.moveaxis(i, 0, 1).reshape(q.shape[0], -1)
        # padded rows score over zero-vectors; mask them out
        valid = i < self.n
        s = np.where(valid, s, -np.inf)
        order = np.argsort(-s, axis=1)[:, :k]
        rows = np.arange(q.shape[0])[:, None]
        return s[rows, order], i[rows, order]
