from bioengine_tpu.rpc.schema import schema_method
from bioengine_tpu.rpc.client import connect_to_server
from bioengine_tpu.rpc.server import RpcServer

__all__ = ["schema_method", "connect_to_server", "RpcServer"]
