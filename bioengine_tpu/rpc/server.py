"""The control-plane RPC server.

Replaces the external Hypha server the reference depends on (the worker
connects OUT to hypha.aicell.io and registers its service dict, ref
bioengine/worker/worker.py:522-664). Here the control plane is part of
the framework: an aiohttp WebSocket server hosting a service registry
with token auth and caller-context injection. A worker can either run
this server itself (standalone mode) or connect to a remote instance —
the same two topologies the reference supports with Hypha.

Capabilities:
- token issue/validate (``generate_token`` with expiry; admin users)
- service registration from any connected client or in-process object
- method calls routed caller -> provider with ``context`` injection
  (``config.require_context``, same convention as the reference's
  services, ref bioengine/utils/permissions.py create_context)
- service listing/metadata incl. method schemas
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import secrets
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from aiohttp import WSMsgType, web

from bioengine_tpu.rpc import protocol
from bioengine_tpu.rpc.schema import extract_schema
from bioengine_tpu.rpc.transport import Codec, RpcStats, TransportConfig
from bioengine_tpu.testing import faults
from bioengine_tpu.utils import metrics, tracing
from bioengine_tpu.utils.logger import create_logger
from bioengine_tpu.utils.tasks import spawn_supervised


def _to_jsonable(obj: Any) -> Any:
    """Numpy-aware conversion for the JSON HTTP bridge (service results
    may carry arrays, e.g. segmentation masks). Non-finite floats
    become null: Python's json emits bare NaN/Infinity literals, which
    browsers' JSON.parse rejects — a diverged training loss must not
    break the frontend."""
    import math

    import numpy as np

    if isinstance(obj, np.ndarray):
        if np.issubdtype(obj.dtype, np.floating) and not np.isfinite(obj).all():
            # vectorized: one NaN in a megapixel map must not trigger
            # per-element Python recursion
            masked = obj.astype(object)
            masked[~np.isfinite(obj)] = None
            return masked.tolist()
        return obj.tolist()
    if isinstance(obj, np.generic):
        obj = obj.item()
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


@dataclass
class TokenInfo:
    user_id: str
    workspace: str
    expires_at: float
    is_admin: bool = False


@dataclass
class ServiceEntry:
    service_id: str
    workspace: str
    owner_client: Optional[str]      # ws connection id; None = in-process
    definition: dict[str, Any]
    methods: dict[str, Callable] = field(default_factory=dict)  # in-process only
    schemas: dict[str, dict] = field(default_factory=dict)

    @property
    def full_id(self) -> str:
        return f"{self.workspace}/{self.service_id}"


class RpcServer:
    """In-process + WebSocket service registry and call router."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        admin_users: Optional[list[str]] = None,
        default_workspace: str = "bioengine",
        token_ttl_seconds: float = 3600 * 24,
        shm_store: Any = "auto",
        transport_config: Optional[TransportConfig] = None,
        inline_dispatch: Optional[bool] = None,
        uds_path: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        # optional same-host listener: serving the same /ws endpoint on
        # a unix-domain socket skips the TCP stack — the cheap wire for
        # co-located workers (clients dial ``unix://<path>``)
        self.uds_path = uds_path
        self._uds_site: Optional[web.UnixSite] = None
        self.default_workspace = default_workspace
        self.admin_users = list(admin_users or [])
        self.token_ttl_seconds = token_ttl_seconds
        self.logger = create_logger("rpc.server", log_file="off")

        self._tokens: dict[str, TokenInfo] = {}
        self._services: dict[str, ServiceEntry] = {}
        self._clients: dict[str, web.WebSocketResponse] = {}
        self._client_users: dict[str, TokenInfo] = {}
        self._pending: dict[str, asyncio.Future] = {}
        self._pending_owner: dict[str, str] = {}  # call_id -> provider client
        # open streaming calls forwarded to remote providers: call_id ->
        # queue of ("item", seq, value) / ("end", result, None) /
        # ("err", 0, exc), drained by call_service_stream
        self._stream_sinks: dict[str, asyncio.Queue] = {}
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None
        self._static_dirs: dict[str, Any] = {}  # name -> Path
        self.artifact_service = None            # attach_artifact_service
        self._mcp_apps: dict[str, Any] = {}     # app_id -> AppServiceProxy
        # zero-copy data plane: one Codec per websocket client, all
        # feeding one server-wide RpcStats (surfaced by describe())
        self.transport_config = transport_config or TransportConfig.from_env()
        self.stats = RpcStats()
        self._client_codecs: dict[str, Codec] = {}
        # capability sets each ws client declared at its handshake
        # (oob/trace live on the codec; the rest are looked up here)
        self._client_protos: dict[str, frozenset[str]] = {}
        self._shm_store_cfg = shm_store
        self._shm_store: Any = None
        # microsecond hot path: an untraced CALL whose target is a
        # LOCAL sync method is executed inline from the read loop —
        # no asyncio task per request (~10-20us saved per call).
        # (service_id, method) -> eligible; cleared on (un)register.
        self._inline_sync: dict[tuple, bool] = {}
        self._inline_dispatch = (
            inline_dispatch
            if inline_dispatch is not None
            else os.environ.get("BIOENGINE_RPC_INLINE_DISPATCH", "1") != "0"
        )
        self._shm_nonces: dict[str, tuple[str, bytes]] = {}  # client -> (key, nonce)
        # controller fencing epoch (set by ServeController.attach_rpc):
        # advertised in the welcome so a connecting host can spot a
        # stale (wedged-then-revived) controller before any verbs flow
        self.epoch: Optional[int] = None

    # ---- lifecycle ----------------------------------------------------------

    def _resolve_shm_store(self) -> Any:
        """The same-host fast-path segment. ``"auto"`` attaches (or
        creates) the shared native segment when the toolchain allows;
        an explicit store instance is used as-is (how tests wire a
        LocalObjectStore through both ends in-process); None disables.
        Auto failures are silent by design — the wire path is always
        sufficient."""
        cfg = self._shm_store_cfg
        if cfg is None:
            return None
        if cfg != "auto":
            return cfg
        import os as _os

        if _os.environ.get("BIOENGINE_RPC_SHM", "1") == "0":
            return None
        from bioengine_tpu.native import store as native_store

        if not native_store.native_available():
            return None
        try:
            name = _os.environ.get("BIOENGINE_RPC_STORE_NAME", "bioengine-rpc")
            cap_mb = float(_os.environ.get("BIOENGINE_RPC_STORE_MB", "256"))
            return native_store.SharedObjectStore(
                name, capacity=int(cap_mb * 1024 * 1024), create="attach"
            )
        except Exception as e:  # noqa: BLE001 — degrade to wire frames
            self.logger.warning(f"shm store unavailable ({e}); wire-only")
            return None

    async def start(self) -> str:
        # the first native-store probe may BUILD the ctypes lib
        # (subprocess cc) — seconds of work that must not sit on the loop
        self._shm_store = await asyncio.to_thread(self._resolve_shm_store)
        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_get("/ws", self._handle_ws)
        app.router.add_get("/health/liveness", self._handle_health)
        app.router.add_get("/services", self._handle_list_http)
        # Prometheus scrape surface: the process-wide metrics registry
        # (request latency histograms, transport counters, serving
        # gauges) in text exposition format — docs/observability.md
        app.router.add_get("/metrics", self._handle_metrics)
        # JSON-over-HTTP bridge: what browser frontends use (the
        # reference's frontends call Hypha services from JS, ref
        # apps/cellpose-finetuning/frontend/index.html; here the bridge
        # is part of the framework's own server)
        app.router.add_post("/call/{service_id}/{method}", self._handle_call_http)
        # dynamically registered app frontends (register_static_dir)
        app.router.add_get("/apps/{name}", self._handle_static)
        app.router.add_get("/apps/{name}/{rest:.*}", self._handle_static)
        # artifact manager HTTP surface (attach_artifact_service)
        app.router.add_route(
            "*", "/artifacts{tail:.*}", self._handle_artifacts
        )
        # per-app MCP endpoints (register_mcp_app)
        app.router.add_post("/mcp/{name}", self._handle_mcp)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        self.port = self._site._server.sockets[0].getsockname()[1]
        if self.uds_path:
            try:
                os.unlink(self.uds_path)  # stale socket from a crash
            except OSError:
                pass
            self._uds_site = web.UnixSite(self._runner, self.uds_path)
            await self._uds_site.start()
            self.logger.info(f"RPC server also on unix://{self.uds_path}")
        self.logger.info(f"RPC server listening on ws://{self.host}:{self.port}/ws")
        return self.url

    async def stop(self) -> None:
        for ws in list(self._clients.values()):
            await ws.close()
        if self._runner:
            await self._runner.cleanup()
        if self.uds_path:
            try:
                os.unlink(self.uds_path)
            except OSError:
                pass
        for codec in self._client_codecs.values():
            codec.close()
        self._client_codecs.clear()
        if self._shm_store is not None:
            self._shm_store.close()  # segment stays for other processes
            self._shm_store = None

    def describe(self) -> dict:
        """Control-plane + data-plane observability: who's connected,
        what's registered, and the transport counters (bytes, frames,
        chunked sends, encode/decode seconds, shm hit-rate)."""
        d = {
            "url": self.url,
            "uds_path": self.uds_path,
            "services": len(self._services),
            "clients": len(self._clients),
            "transport": self.stats.as_dict(),
            "shm": None,
        }
        if self._shm_store is not None:
            shm_clients = sum(
                1 for c in self._client_codecs.values() if c.shm_store is not None
            )
            try:
                store_stats = self._shm_store.stats()
            except Exception as e:  # noqa: BLE001 — stats never break status
                store_stats = {"error": str(e)}
            d["shm"] = {
                "store": self._shm_store.name,
                "negotiated_clients": shm_clients,
                **store_stats,
            }
        return d

    @property
    def url(self) -> str:
        return f"ws://{self.host}:{self.port}/ws"

    @property
    def http_url(self) -> str:
        """Advertisable base URL: a wildcard bind resolves to this
        machine's routable address, never 'http://0.0.0.0:...'."""
        host = self.host
        if host in ("0.0.0.0", "::"):
            from bioengine_tpu.utils.network import get_internal_ip

            host = get_internal_ip()
        return f"http://{host}:{self.port}"

    # ---- tokens -------------------------------------------------------------

    def issue_token(
        self,
        user_id: str,
        workspace: Optional[str] = None,
        ttl_seconds: Optional[float] = None,
        is_admin: Optional[bool] = None,
        token_value: Optional[str] = None,
    ) -> str:
        # token_value lets the worker honor a pre-shared admin token
        # (env BIOENGINE_ADMIN_TOKEN) instead of a generated one.
        # Auth tokens MUST be crypto-random (issuance is login-rate,
        # not request-rate, so the urandom cost is fine here).
        # bioengine: ignore[BE-PERF-302]
        token = token_value or secrets.token_urlsafe(32)
        # opportunistic expiry sweep: lazy deletion in validate_token
        # only reaps tokens that are presented again — without this,
        # a token minted and never revalidated lives forever
        now = time.time()
        for stale in [
            t for t, info in self._tokens.items() if info.expires_at <= now
        ]:
            self._tokens.pop(stale, None)
        self._tokens[token] = TokenInfo(
            user_id=user_id,
            workspace=workspace or self.default_workspace,
            expires_at=time.time() + (ttl_seconds or self.token_ttl_seconds),
            is_admin=user_id in self.admin_users if is_admin is None else is_admin,
        )
        return token

    def validate_token(self, token: str) -> TokenInfo:
        info = self._tokens.get(token)
        if info is None:
            raise PermissionError("Unknown token")
        if time.time() > info.expires_at:
            del self._tokens[token]
            raise PermissionError("Token expired")
        return info

    def _context_for(self, info: TokenInfo) -> dict:
        return {
            "user": {
                "id": info.user_id,
                "email": f"{info.user_id}@bioengine",
                "is_anonymous": info.user_id == "anonymous",
                "roles": ["admin"] if info.is_admin else [],
            },
            "ws": info.workspace,
        }

    # ---- in-process services ------------------------------------------------

    def register_local_service(self, definition: dict[str, Any]) -> ServiceEntry:
        """Register a service whose methods are local callables (the path
        the worker itself uses in standalone mode)."""
        service_id = definition["id"]
        workspace = definition.get("workspace", self.default_workspace)
        methods = {
            k: v for k, v in definition.items() if callable(v)
        }
        entry = ServiceEntry(
            service_id=service_id,
            workspace=workspace,
            owner_client=None,
            definition={
                k: v for k, v in definition.items() if not callable(v)
            },
            methods=methods,
            schemas={
                k: getattr(v, "__schema__", None) or extract_schema(v)
                for k, v in methods.items()
            },
        )
        self._services[entry.full_id] = entry
        self._inline_sync.clear()
        self.logger.info(f"Registered local service {entry.full_id}")
        return entry

    def unregister_service(self, full_id: str) -> None:
        self._services.pop(full_id, None)
        self._inline_sync.clear()

    def service_peer_supports(self, full_id: str, capability: str) -> bool:
        """Did the ws client that OWNS ``full_id`` declare ``capability``
        at its handshake? In-process services (owner_client None) share
        this process's code and support everything we do. The mesh
        planner gates cross-host shard placement on this — a legacy
        worker host that never declared ``mesh1`` must not be handed a
        ``mesh_shard`` start it cannot honor."""
        entry = self._services.get(full_id)
        if entry is None:
            return False
        if entry.owner_client is None:
            return True
        return capability in self._client_protos.get(
            entry.owner_client, frozenset()
        )

    def list_services(self, workspace: Optional[str] = None) -> list[dict]:
        out = []
        for entry in self._services.values():
            if workspace and entry.workspace != workspace:
                continue
            out.append(
                {
                    "id": entry.full_id,
                    "name": entry.definition.get("name", entry.service_id),
                    "type": entry.definition.get("type", "generic"),
                    "description": entry.definition.get("description", ""),
                    "config": entry.definition.get("config", {}),
                    "methods": sorted(entry.schemas),
                }
            )
        return out

    async def call_service_method(
        self,
        full_id: str,
        method: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        caller: Optional[TokenInfo] = None,
        timeout: float = 300.0,
    ) -> Any:
        """Route a call to an in-process or remote-client service.

        ``visibility: "protected"`` services (worker-host replica verbs,
        internal control surfaces) accept only admin callers; the
        in-process path (``caller=None`` — the controller itself) is
        trusted. Public services do their own per-method enforcement."""
        kwargs = dict(kwargs or {})
        entry = self._find_service(full_id)
        visibility = entry.definition.get("config", {}).get(
            "visibility", "public"
        )
        if visibility == "protected" and caller is not None and not caller.is_admin:
            raise PermissionError(
                f"service '{full_id}' is protected (admin required)"
            )
        require_context = entry.definition.get("config", {}).get(
            "require_context", False
        )
        if require_context:
            kwargs["context"] = self._context_for(
                caller
                or TokenInfo("anonymous", self.default_workspace, time.time() + 60)
            )
        if entry.owner_client is None:
            fn = entry.methods.get(method)
            if fn is None:
                raise AttributeError(f"{full_id} has no method '{method}'")
            # gate the attr-dict build on the sampled check — this
            # runs once per local dispatch on the unsampled hot path
            with (
                tracing.span("rpc.dispatch", service=full_id, method=method)
                if tracing.sampled()
                else tracing.NOOP_SPAN
            ):
                result = fn(*args, **kwargs)
                if asyncio.iscoroutine(result):
                    result = await result
            return result
        # remote provider: forward over its websocket
        ws = self._clients.get(entry.owner_client)
        if ws is None or ws.closed:
            raise ConnectionError(f"Provider for {full_id} is gone")
        call_id = tracing.new_id()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[call_id] = fut
        self._pending_owner[call_id] = entry.owner_client
        msg = {
            "t": protocol.CALL,
            "call_id": call_id,
            "service_id": full_id,
            "method": method,
            "args": list(args),
            "kwargs": kwargs,
        }
        # carry the caller's sampled trace context to the provider —
        # only when that provider declared trace1 at its handshake
        # (legacy peers see a byte-identical CALL)
        codec = self._client_codecs.get(entry.owner_client)
        ctx = tracing.current_trace()
        if codec is not None and codec.trace and ctx is not None and ctx.sampled:
            msg["trace"] = ctx.to_wire()
        try:
            with (
                tracing.span("rpc.call", service=full_id, method=method)
                if tracing.sampled()
                else tracing.NOOP_SPAN
            ):
                await self._send(ws, codec, msg)
                return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(call_id, None)
            self._pending_owner.pop(call_id, None)

    async def call_service_stream(
        self,
        full_id: str,
        method: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        caller: Optional[TokenInfo] = None,
        timeout: float = 300.0,
    ):
        """Streaming counterpart of ``call_service_method``: async-
        iterates the items of an async-generator service method.

        Same permission/context rules. Local providers run in-process;
        remote providers must have declared ``stream1`` at their
        handshake (their items arrive as STREAM frames routed into a
        per-call queue and re-yielded here, so in-process and remote
        callers share one ordering/truncation contract). ``timeout`` is
        a per-item inactivity bound, not a whole-stream one — a healthy
        generation outlives any unary deadline."""
        kwargs = dict(kwargs or {})
        entry = self._find_service(full_id)
        visibility = entry.definition.get("config", {}).get(
            "visibility", "public"
        )
        if visibility == "protected" and caller is not None and not caller.is_admin:
            raise PermissionError(
                f"service '{full_id}' is protected (admin required)"
            )
        if entry.definition.get("config", {}).get("require_context", False):
            kwargs["context"] = self._context_for(
                caller
                or TokenInfo("anonymous", self.default_workspace, time.time() + 60)
            )
        if entry.owner_client is None:
            fn = entry.methods.get(method)
            if fn is None:
                raise AttributeError(f"{full_id} has no method '{method}'")
            result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
            if not hasattr(result, "__aiter__"):
                # unary method under a streaming call: one-item stream
                yield result
                return
            try:
                async for item in result:
                    yield item
            finally:
                # closing THIS generator must deterministically close
                # the provider's, so its finally blocks run now rather
                # than at GC
                with contextlib.suppress(Exception):
                    await result.aclose()
            return
        # remote provider: forward as a streaming CALL, drain the sink
        if not self.service_peer_supports(entry.full_id, protocol.PROTO_STREAM1):
            raise RuntimeError(
                f"provider of '{full_id}' does not support streaming "
                "calls (stream1)"
            )
        ws = self._clients.get(entry.owner_client)
        if ws is None or ws.closed:
            raise ConnectionError(f"Provider for {full_id} is gone")
        call_id = tracing.new_id()
        q: asyncio.Queue = asyncio.Queue()
        self._stream_sinks[call_id] = q
        self._pending_owner[call_id] = entry.owner_client
        msg = {
            "t": protocol.CALL,
            "call_id": call_id,
            "service_id": entry.full_id,
            "method": method,
            "args": list(args),
            "kwargs": kwargs,
            "stream": True,
        }
        codec = self._client_codecs.get(entry.owner_client)
        ctx = tracing.current_trace()
        if codec is not None and codec.trace and ctx is not None and ctx.sampled:
            msg["trace"] = ctx.to_wire()
        expected = 0
        try:
            await self._send(ws, codec, msg)
            while True:
                kind, a, b = await asyncio.wait_for(q.get(), timeout)
                if kind == "item":
                    if a != expected:
                        raise ConnectionError(
                            f"stream {call_id} gap: expected item "
                            f"{expected}, got {a}"
                        )
                    expected += 1
                    yield b
                elif kind == "end":
                    n = a.get("n") if isinstance(a, dict) else None
                    if n is not None and n != expected:
                        raise ConnectionError(
                            f"stream {call_id} truncated: provider sent "
                            f"{n} items, received {expected}"
                        )
                    return
                else:
                    raise b
        finally:
            self._stream_sinks.pop(call_id, None)
            self._pending_owner.pop(call_id, None)

    def _find_service(self, full_id: str) -> ServiceEntry:
        if full_id in self._services:
            return self._services[full_id]
        # allow bare ids (unique across workspaces) like the reference's
        # service lookup convenience
        matches = [
            e for e in self._services.values() if e.service_id == full_id
        ]
        if len(matches) == 1:
            return matches[0]
        raise KeyError(f"Service '{full_id}' not found")

    # ---- websocket handling -------------------------------------------------

    async def _handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "services": len(self._services)})

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            body=metrics.render_prometheus().encode(),
            headers={
                "Content-Type": "text/plain; version=0.0.4; charset=utf-8"
            },
        )

    async def _handle_list_http(self, request: web.Request) -> web.Response:
        return web.json_response(self.list_services())

    # ---- HTTP bridge + app frontends -----------------------------------------

    def register_static_dir(self, name: str, directory) -> str:
        """Serve ``directory`` at ``/apps/{name}/`` (an app's browser
        frontend). Returns the URL path prefix."""
        from pathlib import Path

        self._static_dirs[name] = Path(directory).resolve()
        return f"/apps/{name}/"

    def unregister_static_dir(self, name: str) -> None:
        self._static_dirs.pop(name, None)

    async def _handle_static(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        root = self._static_dirs.get(name)
        if root is None:
            raise web.HTTPNotFound(reason=f"no frontend '{name}'")
        if "rest" not in request.match_info:
            # /apps/foo -> /apps/foo/ so the page's relative asset URLs
            # resolve inside the frontend dir
            raise web.HTTPFound(f"/apps/{name}/")
        rest = request.match_info.get("rest", "") or "index.html"
        target = (root / rest).resolve()
        if not target.is_relative_to(root):
            raise web.HTTPForbidden(reason="path escapes frontend dir")
        if target.is_dir():
            target = target / "index.html"
        if not target.is_file():
            raise web.HTTPNotFound()
        return web.FileResponse(target)

    def register_mcp_app(self, app_id: str, proxy) -> str:
        """Expose a deployed app as an MCP server at ``/mcp/{app_id}``
        (streamable HTTP, apps/mcp.py). Returns the URL path."""
        self._mcp_apps[app_id] = proxy
        return f"/mcp/{app_id}"

    def unregister_mcp_app(self, app_id: str) -> None:
        self._mcp_apps.pop(app_id, None)

    async def _handle_mcp(self, request: web.Request) -> web.Response:
        from bioengine_tpu.apps.mcp import handle_message

        proxy = self._mcp_apps.get(request.match_info["name"])
        if proxy is None:
            raise web.HTTPNotFound(
                reason=f"no MCP app '{request.match_info['name']}'"
            )
        try:
            caller = self._http_caller(request)
        except PermissionError as e:
            return web.json_response({"error": str(e)}, status=401)
        try:
            body = await request.json()
        except ValueError:
            body = None
        if not isinstance(body, dict):
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": None,
                    "error": {"code": -32700, "message": "parse error"},
                },
                status=400,
            )
        response = await handle_message(
            proxy, body, self._context_for(caller)
        )
        if response is None:  # notification
            return web.Response(status=202)
        return web.json_response(response)

    def attach_artifact_service(self, service) -> None:
        """Serve an ArtifactHttpService at ``/artifacts`` (presigned
        uploads, versioned fetch, static site — apps/artifact_http.py)."""
        self.artifact_service = service

    async def _handle_artifacts(self, request: web.Request) -> web.Response:
        if self.artifact_service is None:
            raise web.HTTPNotFound(reason="no artifact service attached")
        return await self.artifact_service.handle(request)

    def _http_caller(self, request: web.Request) -> TokenInfo:
        token = request.query.get("token", "")
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            token = auth[len("Bearer "):]
        if token:
            return self.validate_token(token)  # PermissionError -> 401
        return TokenInfo("anonymous", self.default_workspace, time.time() + 60)

    async def _handle_call_http(self, request: web.Request) -> web.Response:
        """POST /call/{service_id}/{method} with JSON body
        ``{"args": [...], "kwargs": {...}}`` — the browser-facing call
        path. Same auth + context injection as the websocket plane."""
        try:
            caller = self._http_caller(request)
        except PermissionError as e:
            return web.json_response({"error": str(e)}, status=401)
        try:
            body = await request.json() if request.can_read_body else {}
        except ValueError:
            body = None
        if not isinstance(body, dict):
            return web.json_response({"error": "invalid JSON body"}, status=400)
        service_id = request.match_info["service_id"]
        method = request.match_info["method"]
        # resolve first so only a wrong service/method is a 404 — an app
        # bug raising KeyError inside the call must surface as a 500
        try:
            entry = self._find_service(service_id)
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        if entry.owner_client is None and method not in entry.methods:
            return web.json_response(
                {"error": f"{service_id} has no method '{method}'"}, status=404
            )
        try:
            result = await self.call_service_method(
                entry.full_id,
                method,
                tuple(body.get("args", ())),
                body.get("kwargs", {}),
                caller=caller,
            )
            return web.json_response({"result": _to_jsonable(result)})
        except PermissionError as e:
            return web.json_response({"error": str(e)}, status=403)
        except Exception as e:
            return web.json_response(
                {"error": f"{type(e).__name__}: {e}"}, status=500
            )

    async def _send(
        self, ws: web.WebSocketResponse, codec: Optional[Codec], msg: dict
    ) -> None:
        """Encode per the client's negotiated capabilities and send —
        one websocket message per frame (oversized frames go out as a
        chunk sequence). Large payloads encode off-loop."""
        if faults.ACTIVE:
            await faults.hit("rpc.server.send", drop=ws.close)
        if codec is None:
            codec = Codec(config=self.transport_config, stats=self.stats)
        if codec.fast:
            # small-response hot path: one sync encode attempt, one
            # send — skips the coroutine + payload walk when it hits
            frame = codec.encode_fast_frame(msg)
            if frame is not None:
                await ws.send_bytes(frame)
                return
        for frame in await codec.encode_frames_async(msg):
            await ws.send_bytes(frame)

    async def _handle_ws(self, request: web.Request) -> web.WebSocketResponse:
        token = request.query.get("token", "")
        try:
            if token:
                info = self.validate_token(token)
            else:
                info = TokenInfo(
                    "anonymous", self.default_workspace, time.time() + 86400
                )
        except PermissionError as e:
            raise web.HTTPUnauthorized(reason=str(e))

        ws = web.WebSocketResponse(max_msg_size=self.transport_config.max_msg_size)
        await ws.prepare(request)
        client_id = uuid.uuid4().hex
        codec = Codec(config=self.transport_config, stats=self.stats)
        # the client declares codec support at handshake time; anything
        # it doesn't declare gets legacy single-blob frames forever
        declared = request.query.get("proto", "").split(",")
        codec.oob = protocol.PROTO_OOB1 in declared
        codec.trace = protocol.PROTO_TRACE1 in declared
        codec.fast = protocol.PROTO_FAST1 in declared
        self._clients[client_id] = ws
        # the full declared set outlives the codec flags: server-side
        # capability gates (e.g. the controller refusing to plan a
        # cross-host mesh onto a pre-mesh1 host) ask via
        # service_peer_supports
        self._client_protos[client_id] = frozenset(p for p in declared if p)
        self._client_users[client_id] = info
        self._client_codecs[client_id] = codec
        welcome = {
            "t": "welcome",
            "client_id": client_id,
            "workspace": info.workspace,
            "user_id": info.user_id,
            "protocols": [
                protocol.PROTO_OOB1,
                protocol.PROTO_TRACE1,
                protocol.PROTO_TELEM1,
                protocol.PROTO_MESH1,
                protocol.PROTO_EPOCH1,
                protocol.PROTO_FAST1,
                protocol.PROTO_STREAM1,
            ],
        }
        if self.epoch is not None:
            welcome["epoch"] = self.epoch
        if codec.oob and self._shm_store is not None:
            # same-host probe: the client must read this nonce OUT OF
            # the segment and echo it back — proof the two processes
            # map the same shm, not just claim the same store name
            probe_key = f"rpc/probe/{client_id}"
            nonce = secrets.token_bytes(16)
            try:
                if self._shm_store.try_put(probe_key, nonce):
                    self._shm_nonces[client_id] = (probe_key, nonce)
                    welcome["shm"] = {
                        "name": self._shm_store.name,
                        "probe_key": probe_key,
                    }
            except Exception as e:  # noqa: BLE001 — probe failure = wire-only
                self.logger.warning(f"shm probe put failed: {e}")
        await self._send(ws, codec, welcome)
        try:
            async for msg in ws:
                if msg.type != WSMsgType.BINARY:
                    continue
                raw = msg.data
                try:
                    if protocol.is_fast_frame(raw):
                        # BEFS: sync decode, nothing pinned to drain.
                        # A fast frame is only ever CALL or RESULT and
                        # a fast CALL can never carry a trace
                        # attachment (the encoder rejects it), so the
                        # inline gate here is just the memoized plan —
                        # and the hot path runs handler-from-tuple
                        # without ever materializing the envelope dict
                        parsed = (
                            codec.decode_fast_call_frame(raw)
                            if self._inline_dispatch
                            else None
                        )
                        if parsed is not None:
                            call_id, sid, mth, c_args, c_kwargs = parsed
                            plan = self._inline_call_plan(sid, mth)
                            if plan:
                                await self._handle_call_inline(
                                    ws, codec, info,
                                    call_id, sid, c_args, c_kwargs,
                                    plan,
                                )
                                continue
                            await self._dispatch(client_id, ws, {
                                "t": protocol.CALL,
                                "call_id": call_id,
                                "service_id": sid,
                                "method": mth,
                                "args": c_args,
                                "kwargs": c_kwargs,
                            })
                            continue
                        # per-token stream frames from a provider ride
                        # straight into the caller's sink — no envelope
                        # dict on the per-item hot path
                        sparsed = codec.decode_fast_stream_frame(raw)
                        if sparsed is not None:
                            sink = self._stream_sinks.get(sparsed[0])
                            if sink is not None:
                                sink.put_nowait(
                                    ("item", sparsed[1], sparsed[2])
                                )
                            continue
                        await self._dispatch(
                            client_id, ws, codec.decode_fast_frame(raw)
                        )
                        continue
                    try:
                        decoded = await codec.decode_async(raw)
                        if decoded is None:
                            continue  # mid-reassembly chunk
                        await self._dispatch(client_id, ws, decoded)
                    finally:
                        # one-shot shm payloads whose consumers
                        # finished leave the arena as soon as possible
                        codec.drain_pins()
                except Exception as e:  # keep the connection alive
                    self.logger.error(f"dispatch error: {e}")
        finally:
            self._drop_client(client_id)
        return ws

    def _drop_client(self, client_id: str) -> None:
        self._clients.pop(client_id, None)
        self._client_users.pop(client_id, None)
        self._client_protos.pop(client_id, None)
        codec = self._client_codecs.pop(client_id, None)
        if codec is not None:
            codec.close()
        probe = self._shm_nonces.pop(client_id, None)
        if probe is not None and self._shm_store is not None:
            try:
                self._shm_store.delete(probe[0])
            except Exception as e:  # noqa: BLE001 — client may have deleted it
                self.logger.debug(f"probe cleanup raced: {e}")
        for full_id in [
            fid
            for fid, e in self._services.items()
            if e.owner_client == client_id
        ]:
            del self._services[full_id]
            self._inline_sync.clear()
            self.logger.info(f"Dropped service {full_id} (client disconnect)")
        # fail every in-flight call routed to this client NOW — without
        # this, callers hang for the full RPC timeout after a provider
        # crash (a worker-host SIGKILL must fail fast so the serving
        # controller can restart the replica elsewhere)
        for call_id, owner in list(self._pending_owner.items()):
            if owner != client_id:
                continue
            fut = self._pending.get(call_id)
            if fut and not fut.done():
                fut.set_exception(
                    ConnectionError(
                        f"provider client {client_id} disconnected mid-call"
                    )
                )
            # streams in flight from this provider fail with the same
            # typed error, immediately — a caller mid-generation must
            # see the drop now, not an inter-token timeout later
            sink = self._stream_sinks.get(call_id)
            if sink is not None:
                sink.put_nowait(
                    ("err", 0, ConnectionError(
                        f"provider client {client_id} disconnected mid-stream"
                    ))
                )

    async def _dispatch(
        self, client_id: str, ws: web.WebSocketResponse, msg: dict
    ) -> None:
        t = msg.get("t")
        info = self._client_users[client_id]
        codec = self._client_codecs.get(client_id)
        if t == protocol.CALL:
            # checked first — CALL dominates the message mix.
            # Uncontended small-request path: a sync local handler runs
            # for ~microseconds either way — spawning a supervised task
            # just to host it costs more than the call itself. Inline
            # keeps ordering per connection (the read loop is already
            # sequential); async handlers and remote providers still
            # take the task path so pipelined calls interleave.
            plan = (
                self._inline_dispatch
                and "trace" not in msg
                and self._inline_call_plan(
                    msg.get("service_id"), msg.get("method")
                )
            )
            if plan:
                await self._handle_call_inline(
                    ws, codec, info,
                    msg.get("call_id"), msg.get("service_id"),
                    msg.get("args", ()), msg.get("kwargs") or {},
                    plan,
                )
            else:
                spawn_supervised(
                    self._handle_call(ws, codec, info, msg),
                    name="rpc-handle-call",
                    logger=self.logger,
                )
        elif t == protocol.PING:
            await self._send(ws, codec, {"t": protocol.PONG, "ts": time.time()})
        elif t == protocol.SHM_ACK:
            # the client read the probe nonce out of the segment and
            # echoed it: both processes provably map the same shm, so
            # large payloads to this client may ride the store
            probe = self._shm_nonces.pop(client_id, None)
            verified = (
                probe is not None
                and codec is not None
                and self._shm_store is not None
                and bytes(msg.get("nonce") or b"") == probe[1]
            )
            if verified:
                codec.enable_shm(self._shm_store)
                self.logger.info(
                    f"shm fast path negotiated with client {client_id}"
                )
            if probe is not None and self._shm_store is not None:
                try:
                    self._shm_store.delete(probe[0])
                except Exception as e:  # noqa: BLE001 — client may have deleted it
                    self.logger.debug("probe cleanup raced: %s", e)
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": bool(verified),
                },
            )
        elif t == protocol.REGISTER:
            definition = msg["definition"]
            entry = ServiceEntry(
                service_id=definition["id"],
                workspace=info.workspace,
                owner_client=client_id,
                definition={
                    k: v for k, v in definition.items() if k != "methods"
                },
                schemas=definition.get("methods", {}),
            )
            self._services[entry.full_id] = entry
            self._inline_sync.clear()
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": {"id": entry.full_id},
                },
            )
        elif t == protocol.UNREGISTER:
            entry = self._services.get(msg["service_id"])
            if entry and entry.owner_client == client_id:
                del self._services[msg["service_id"]]
                self._inline_sync.clear()
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": True,
                },
            )
        elif t == protocol.TOKEN:
            if not info.is_admin:
                await self._send_error(
                    ws, codec, msg.get("call_id"), PermissionError("admin required")
                )
                return
            # clients send explicit None for unset fields — `or` fallback,
            # not a .get default, so None resolves to the caller's identity
            token = self.issue_token(
                user_id=msg.get("user_id") or info.user_id,
                workspace=msg.get("workspace") or info.workspace,
                ttl_seconds=msg.get("ttl_seconds"),
                is_admin=bool(msg.get("is_admin")),
            )
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": token,
                },
            )
        elif t == protocol.LIST:
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": msg.get("call_id"),
                    "result": self.list_services(msg.get("workspace")),
                },
            )
        elif t == protocol.STREAM:
            sink = self._stream_sinks.get(msg.get("call_id", ""))
            if sink is not None:
                sink.put_nowait(("item", msg.get("seq", 0), msg.get("item")))
        elif t == protocol.RESULT:
            if msg.get("spans"):
                # spans a provider recorded while serving a sampled
                # call — absorbed here so the control-plane process
                # can hand back one cross-process tree via get_traces
                tracing.absorb_spans(msg["spans"])
            call_id = msg.get("call_id", "")
            fut = self._pending.get(call_id)
            if fut and not fut.done():
                fut.set_result(msg.get("result"))
            else:
                sink = self._stream_sinks.get(call_id)
                if sink is not None:
                    sink.put_nowait(("end", msg.get("result"), None))
        elif t == protocol.ERROR:
            if msg.get("spans"):
                tracing.absorb_spans(msg["spans"])
            call_id = msg.get("call_id", "")
            err = msg.get("error")
            if not isinstance(err, Exception):
                err = RuntimeError(str(err))
            fut = self._pending.get(call_id)
            if fut and not fut.done():
                fut.set_exception(err)
            else:
                sink = self._stream_sinks.get(call_id)
                if sink is not None:
                    sink.put_nowait(("err", 0, err))

    def _inline_call_plan(self, service_id, method):
        """Resolve a CALL target to a (fn, require_context, protected)
        plan when it is a local (in-process) plain-function method,
        else False. Memoized per (service_id, method) — the lookup
        runs on every request, so it must cost two dict hits, not an
        ``iscoroutinefunction`` + config walk. Any registry mutation
        clears the memo."""
        key = (service_id, method)
        plan = self._inline_sync.get(key)
        if plan is None:
            entry = self._services.get(service_id)
            fn = (
                entry.methods.get(method)
                if entry is not None and entry.owner_client is None
                else None
            )
            if fn is None or asyncio.iscoroutinefunction(fn):
                plan = False
            else:
                cfg = entry.definition.get("config", {})
                plan = (
                    fn,
                    bool(cfg.get("require_context", False)),
                    cfg.get("visibility", "public") == "protected",
                )
            self._inline_sync[key] = plan
        return plan

    async def _handle_call_inline(
        self,
        ws: web.WebSocketResponse,
        codec: Optional[Codec],
        info: TokenInfo,
        call_id,
        service_id,
        args,
        kwargs: dict,
        plan: tuple,
    ) -> None:
        """The microsecond dispatch path for an untraced CALL whose
        target resolved to a local sync method: the permission and
        context rules of ``call_service_method`` applied from the
        memoized plan, no span machinery (nothing is sampled here —
        the inline branch requires an untraced CALL), no pin drain
        (small frames carry no shm refs). Takes the envelope fields
        unpacked so the BEFS read-loop path never builds the dict."""
        fn, require_context, protected = plan
        try:
            if protected and not info.is_admin:
                raise PermissionError(
                    f"service '{service_id}' is protected "
                    "(admin required)"
                )
            if require_context:
                kwargs = dict(kwargs)
                kwargs["context"] = self._context_for(info)
            result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
            if codec is not None and codec.fast:
                # straight from return value to wire: no RESULT dict
                # unless the fast encode bails (oversize payload)
                if faults.ACTIVE:
                    await faults.hit("rpc.server.send", drop=ws.close)
                frame = codec.encode_fast_result_frame(call_id, result)
                if frame is not None:
                    await ws.send_bytes(frame)
                    return
            await self._send(
                ws,
                codec,
                {
                    "t": protocol.RESULT,
                    "call_id": call_id,
                    "result": result,
                },
            )
        except Exception as e:
            await self._send_error(ws, codec, call_id, e)

    async def _handle_call(
        self,
        ws: web.WebSocketResponse,
        codec: Optional[Codec],
        info: TokenInfo,
        msg: dict,
    ) -> None:
        # a sampled caller's trace context wraps the whole dispatch:
        # spans recorded here (and piggybacked by a downstream
        # provider) ship back to the caller on the response frame
        ctx = token = None
        if codec is not None and codec.trace and isinstance(
            msg.get("trace"), dict
        ):
            ctx = tracing.TraceContext.from_wire(msg["trace"])
            token = tracing.activate(ctx)
        try:
            if msg.get("stream"):
                # streaming call: re-send each item to the caller as it
                # arrives (provider-side ordering is preserved by the
                # sequential per-websocket read loop), then close with
                # the counting RESULT
                seq = 0
                agen = self.call_service_stream(
                    msg["service_id"],
                    msg["method"],
                    tuple(msg.get("args", ())),
                    msg.get("kwargs", {}),
                    caller=info,
                )
                try:
                    async for item in agen:
                        await self._send_stream_item(
                            ws, codec, msg.get("call_id"), seq, item
                        )
                        seq += 1
                except BaseException:
                    # a failed send mid-stream must not leave the
                    # provider's generator suspended until GC — its
                    # finally blocks release decode slots / ongoing
                    # counts, so close it deterministically
                    with contextlib.suppress(Exception):
                        await agen.aclose()
                    raise
                result = {"n": seq}
            else:
                result = await self.call_service_method(
                    msg["service_id"],
                    msg["method"],
                    tuple(msg.get("args", ())),
                    msg.get("kwargs", {}),
                    caller=info,
                )
            response = {
                "t": protocol.RESULT,
                "call_id": msg.get("call_id"),
                "result": result,
            }
            if ctx is not None and ctx.collector:
                response["spans"] = ctx.collector
            await self._send(ws, codec, response)
        except Exception as e:
            await self._send_error(
                ws,
                codec,
                msg.get("call_id"),
                e,
                spans=ctx.collector if ctx is not None else None,
            )
        finally:
            if token is not None:
                tracing.deactivate(token)
            if codec is not None:
                # call args decoded from shm refs are dead once the
                # handler returns — release their pins promptly
                codec.drain_pins()

    async def _send_stream_item(
        self,
        ws: web.WebSocketResponse,
        codec: Optional[Codec],
        call_id,
        seq: int,
        item,
    ) -> None:
        """One stream item to a caller — fast frame first (per-token
        sends are the stream plane's hot path), STREAM envelope on
        fallback."""
        if codec is not None and codec.fast:
            if faults.ACTIVE:
                await faults.hit("rpc.server.send", drop=ws.close)
            frame = codec.encode_fast_stream_frame(call_id, seq, item)
            if frame is not None:
                await ws.send_bytes(frame)
                return
        await self._send(
            ws,
            codec,
            {"t": protocol.STREAM, "call_id": call_id, "seq": seq, "item": item},
        )

    async def _send_error(
        self,
        ws: web.WebSocketResponse,
        codec: Optional[Codec],
        call_id: Optional[str],
        error: Exception,
        spans: Optional[list] = None,
    ) -> None:
        msg = {"t": protocol.ERROR, "call_id": call_id, "error": error}
        if spans:
            msg["spans"] = spans
        await self._send(ws, codec, msg)
