"""``schema_method`` — annotate service methods with a callable schema.

The reference's app services expose ``@schema_method`` functions whose
signatures/docstrings become JSON schemas for agent consumption (the
hypha-rpc convention; the proxy wraps one schema_function per entry
method, ref bioengine/apps/proxy_deployment.py:477-597). Same contract
here: decorate a method, and the service layer publishes its schema.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, get_type_hints

_TYPE_MAP = {
    int: "integer",
    float: "number",
    str: "string",
    bool: "boolean",
    list: "array",
    dict: "object",
    bytes: "string",
    type(None): "null",
}


def extract_schema(func: Callable) -> dict[str, Any]:
    sig = inspect.signature(func)
    try:
        hints = get_type_hints(func)
    except Exception:
        hints = {}
    properties: dict[str, Any] = {}
    required: list[str] = []
    for name, param in sig.parameters.items():
        if name in ("self", "cls", "context"):
            continue
        prop: dict[str, Any] = {}
        hint = hints.get(name)
        if hint in _TYPE_MAP:
            prop["type"] = _TYPE_MAP[hint]
        if param.default is not inspect.Parameter.empty:
            prop["default"] = param.default
        else:
            if param.kind not in (
                inspect.Parameter.VAR_POSITIONAL,
                inspect.Parameter.VAR_KEYWORD,
            ):
                required.append(name)
        properties[name] = prop
    return {
        "name": func.__name__,
        "description": inspect.getdoc(func) or "",
        "parameters": {
            "type": "object",
            "properties": properties,
            "required": required,
        },
    }


def schema_method(func: Callable) -> Callable:
    """Mark a method as a published service endpoint with a schema."""
    func.__schema__ = extract_schema(func)
    func.__is_schema_method__ = True
    return func


def is_schema_method(func: Any) -> bool:
    return callable(func) and getattr(func, "__is_schema_method__", False)
